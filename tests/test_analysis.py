"""jaxlint (cocoa_tpu/analysis): per-rule known-good/known-bad fixtures,
the PR-2 donation-miss regression, the mesh-API inventory completeness
contract, the baseline/suppression machinery, and the dynamic sanitizer
smoke on the CPU drive loop (compile-once + zero unintended device→host
transfers, telemetry-on and -off)."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cocoa_tpu import analysis
from cocoa_tpu.analysis import core, pallas_budget, rules, sanitize
from cocoa_tpu.config import DebugParams, Params
from cocoa_tpu.data.sharding import shard_dataset
from cocoa_tpu.solvers import run_cocoa
from cocoa_tpu.telemetry import events as tele
from cocoa_tpu.telemetry import schema

K = 4


# --- fixture-lint helper ----------------------------------------------------


def lint(tmp_path, code, relpath="fixture.py", rule=None):
    """Lint one source fixture; returns findings (optionally one rule's)."""
    ab = tmp_path / relpath
    ab.parent.mkdir(parents=True, exist_ok=True)
    ab.write_text(code)
    src = core.load_source(str(tmp_path), relpath)
    assert src is not None, "fixture failed to parse"
    sources = {src.path: src}
    found = rules.run_static_rules(sources)
    core.fingerprint_findings(found, sources)
    core.apply_suppressions(found, sources)
    if rule is not None:
        found = [f for f in found if f.rule == rule]
    return found


# --- donation rule ----------------------------------------------------------

PR2_SHAPE = """
import functools
import jax
import jax.numpy as jnp

@functools.partial(jax.jit, donate_argnums=(0, 1))
def round_step(w, alpha, idxs, delta):
    # the PR-2 bug: the donated alpha is read both through .at and bare,
    # so the output cannot alias the donated buffer -> silent full copy
    da = alpha.at[idxs].add(delta) - alpha
    return w + da.sum(), alpha + da
"""

PR2_FIXED = """
import functools
import jax
import jax.numpy as jnp

@functools.partial(jax.jit, donate_argnums=(0, 1))
def round_step(w, alpha, idxs, delta):
    # the PR-2 fix shape: scatter (a0 + d) - a0 into zeros
    da = jnp.zeros_like(alpha).at[idxs].add(delta)
    return w + da.sum(), alpha + da
"""

PR2_NESTED = """
import functools
import jax
from cocoa_tpu.solvers import base

def make_round_step(mesh):
    def per_shard(w, alpha_k, idxs_k):
        delta = w[idxs_k]
        return delta.sum(), alpha_k.at[idxs_k].add(delta) - alpha_k

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def round_step(w, alpha, idxs):
        dw, alpha = base.fanout(per_shard, mesh, w, alpha, idxs)
        return w + dw, alpha

    return round_step
"""


def test_donation_pr2_regression_caught(tmp_path):
    """The exact PR-2 α donation-miss shape is a lint error."""
    found = lint(tmp_path, PR2_SHAPE, rule="donation")
    assert len(found) == 1
    assert "full copy" in found[0].message
    assert "alpha" in found[0].message


def test_donation_pr2_fixed_shape_clean(tmp_path):
    assert lint(tmp_path, PR2_FIXED, rule="donation") == []


def test_donation_pr2_nested_per_shard_caught(tmp_path):
    """The shape as it actually occurred: inside a per_shard fn passed to
    fanout, not lexically inside the jitted def."""
    found = lint(tmp_path, PR2_NESTED, rule="donation")
    assert len(found) == 1
    assert "alpha_k" in found[0].message


def test_donation_index_out_of_range(tmp_path):
    code = """
import jax

def f(w):
    return w * 2

g = jax.jit(f, donate_argnums=(3,))
"""
    found = lint(tmp_path, code, rule="donation")
    assert len(found) == 1
    assert "out of range" in found[0].message


def test_donation_unused_donated_arg(tmp_path):
    code = """
import functools
import jax

@functools.partial(jax.jit, donate_argnums=(1,))
def f(w, alpha):
    return w * 2
"""
    found = lint(tmp_path, code, rule="donation")
    assert len(found) == 1
    assert "never reads" in found[0].message


def test_donation_step_in_solvers_must_donate(tmp_path):
    code = """
import jax

def make_step():
    def round_step(w, idxs):
        return w + idxs.sum()
    return jax.jit(round_step)
"""
    found = lint(tmp_path, code, relpath="cocoa_tpu/solvers/x.py",
                 rule="donation")
    assert len(found) == 1
    assert "donates nothing" in found[0].message
    # the same jit site outside solvers/ is not step-shaped policy
    assert lint(tmp_path, code, relpath="cocoa_tpu/evalsx/x.py",
                rule="donation") == []


def test_donation_good_steps_clean(tmp_path):
    code = """
import functools
import jax

@functools.partial(jax.jit, donate_argnums=(0,))
def round_step(w, idxs):
    return w + idxs.sum()

def make(kernel):
    return jax.jit(kernel, donate_argnums=(0, 1))
"""
    assert lint(tmp_path, code, relpath="cocoa_tpu/solvers/x.py",
                rule="donation") == []


# --- host-sync rule ---------------------------------------------------------

HOST_SYNC_BAD = """
import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

@jax.jit
def f(x):
    v = float(x)                 # scalar sync
    a = np.asarray(x)            # host materialization
    return v + a.sum()

@jax.jit
def g(state):
    def body(s):
        return s + jnp.float32(s.item())   # sync per loop iteration
    return lax.while_loop(lambda s: s < 3, body, state)

@jax.jit
def h(x):
    if x:                        # implicit bool()
        return x
    return -x
"""


def test_host_sync_bad_shapes_caught(tmp_path):
    found = lint(tmp_path, HOST_SYNC_BAD, rule="host-sync")
    msgs = sorted(f.message for f in found)
    assert len(found) == 4, msgs
    assert any("float()" in m for m in msgs)
    assert any("asarray" in m for m in msgs)
    assert any(".item()" in m for m in msgs)
    assert any("implicit bool" in m for m in msgs)


HOST_SYNC_GOOD = """
import functools
import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import io_callback

def tap(i, row):
    # host side: the sanctioned io_callback target may sync freely
    print(int(i), float(row[0]))

@jax.jit
def f(x):
    def body(s):
        io_callback(tap, None, s, x, ordered=True)
        return s + 1
    return lax.while_loop(lambda s: s < 3, body, jnp.int32(0))

@functools.partial(jax.jit, static_argnames=("n", "lam"))
def k(x, n, lam):
    # static args are trace-time python: float()/if are legal
    scale = float(lam * n)
    if n > 4:
        scale = scale * 2.0
    return x * scale + float(x.shape[0])   # shape metadata is static
"""


def test_host_sync_sanctioned_shapes_clean(tmp_path):
    assert lint(tmp_path, HOST_SYNC_GOOD, rule="host-sync") == []


def test_host_sync_repo_drivers_clean():
    """The production drivers/kernels carry no stray host syncs (what
    PR 6's first full-tree run established; keep it true)."""
    findings, _, _ = analysis.run_analysis(with_budget_checks=False)
    bad = [f for f in findings if f.rule == "host-sync" and f.actionable]
    assert bad == [], [f.location() for f in bad]


# --- f64 rule ---------------------------------------------------------------


def test_f64_leak_caught_outside_evals(tmp_path):
    code = """
import jax.numpy as jnp
import numpy as np

def f(x):
    return jnp.asarray(x, dtype=jnp.float64)

def g(x):
    return x.astype("float64")
"""
    found = lint(tmp_path, code, relpath="cocoa_tpu/ops/x.py", rule="f64")
    assert len(found) == 2
    # the same code under evals/ is certificate math — allowed
    assert lint(tmp_path, code, relpath="cocoa_tpu/evals/x.py",
                rule="f64") == []


def test_f64_inline_allow(tmp_path):
    code = """
import numpy as np

def parse(tokens):
    # jaxlint: allow=f64 -- host-side exact parse fixture
    return np.asarray(tokens, dtype=np.float64)
"""
    found = lint(tmp_path, code, relpath="cocoa_tpu/data/x.py", rule="f64")
    assert len(found) == 1
    assert found[0].suppressed
    assert "exact parse" in found[0].suppression_reason


# --- mesh-api inventory -----------------------------------------------------


def test_mesh_inventory_complete():
    """The deprecated/unsupported mesh-API worklist (ROADMAP item 4) is
    exactly the set jaxlint catalogues — every call site named, each with
    a supported-API replacement.  If this fails after editing the mesh
    layer, the refactor either migrated a site (update the count AND the
    baseline) or introduced a new unsupported call (migrate it)."""
    findings, _, _ = analysis.run_analysis(with_budget_checks=False)
    inv = sorted((f.path, f.line, f.message.split("`")[1])
                 for f in findings if f.rule == "mesh-api")
    by_file = {}
    for path, _, api in inv:
        by_file.setdefault(path, []).append(api)
    assert by_file == {
        "cocoa_tpu/parallel/fanout.py": [
            "lax.pcast", "lax.pvary", "jax.shard_map", "jax.shard_map"],
        "cocoa_tpu/parallel/mesh.py": [
            "jax.make_mesh(axis_types=...)", "jax.sharding.AxisType"],
    }, inv
    assert len(inv) == 6
    # every inventory entry must carry its supported-API replacement
    for f in findings:
        if f.rule == "mesh-api":
            assert f.replacement, f.location()


# --- pallas-budget ----------------------------------------------------------


def test_pallas_budget_missing_gate_caught(tmp_path):
    code = """
from jax.experimental import pallas as pl

def kernel(ref, out):
    out[...] = ref[...]

def run(x):
    return pl.pallas_call(kernel, out_shape=x)(x)
"""
    found = lint(tmp_path, code, relpath="cocoa_tpu/ops/x.py",
                 rule="pallas-budget")
    msgs = [f.message for f in found]
    assert any("no *_BUDGET constant" in m for m in msgs)
    assert any("no *_fits gate" in m for m in msgs)


def test_pallas_budget_numeric_checks_clean():
    """The shipped ops modules: budgets under the physical caps, gates
    agreeing with their estimates over the dispatch-realistic sweep."""
    assert pallas_budget.run_budget_checks() == []


def test_pallas_budget_detects_gate_estimate_drift(monkeypatch):
    """Widen the sparse estimate out from under its gate — the sweep must
    notice (this is the 'overflow becomes a lint error' contract)."""
    from cocoa_tpu.ops import pallas_sparse

    # a gate that stops consulting its estimate (the drift shape: a new
    # scratch buffer accounted in the estimate but not gated on)
    monkeypatch.setattr(pallas_sparse, "sparse_kernel_fits",
                        lambda *a, **k: True)
    found = pallas_budget.check_gate_estimate_agreement()
    assert any("exceeds VMEM_BUDGET" in f.message for f in found)


# --- span-hygiene rule ------------------------------------------------------

SPAN_IN_JIT = """
import jax
from cocoa_tpu.telemetry import tracing

@jax.jit
def step(w, alpha):
    with tracing.span("round"):
        return w + alpha.sum(), alpha
"""

SPAN_IN_LAX_BODY = """
import jax
from jax import lax
from cocoa_tpu.telemetry import tracing as _tracing

def run(w):
    def body(s):
        with _tracing.span("chunk"):
            return s + 1.0
    return lax.while_loop(lambda s: s < 10.0, body, w)
"""

TRACED_DECORATOR_ON_JITTED = """
import functools
import jax
from cocoa_tpu.telemetry import tracing

@functools.partial(jax.jit, donate_argnums=(0,))
@tracing.traced("round_step")
def round_step(w, idxs):
    return w + w[idxs].sum()
"""

SPAN_READS_TRACED_VALUE = """
import jax
from jax import lax
from jax.experimental import io_callback
from cocoa_tpu.telemetry import tracing

@jax.jit
def run(w):
    def tap(row):
        # host-side by construction (io_callback target), so spanning is
        # legal — but tagging the enclosing TRACED w syncs it at emit
        with tracing.span("eval", w_now=w):
            pass
    def body(s):
        io_callback(tap, None, s, ordered=True)
        return s + 1.0
    return lax.while_loop(lambda s: s < 3.0, body, w)
"""

SPAN_ON_HOST_CLEAN = """
import jax
from cocoa_tpu.telemetry import tracing

@jax.jit
def step(w):
    return w + 1.0

def drive(w, rounds):
    for t in range(rounds):
        with tracing.span("local_solve", round=t):
            w = step(w)
    with tracing.span("eval", round=rounds):
        gap = float(w.sum())
    return w, gap
"""

SPAN_IN_CALLBACK_CLEAN = """
import jax
from jax import lax
from jax.experimental import io_callback
from cocoa_tpu.telemetry import tracing

def run(w):
    def tap(row):
        # io_callback targets run on the HOST — spans are fine here
        with tracing.span("decode"):
            pass
    def body(s):
        io_callback(tap, None, s, ordered=True)
        return s + 1.0
    return lax.while_loop(lambda s: s < 3.0, body, w)
"""


def test_span_hygiene_span_in_jit_caught(tmp_path):
    found = lint(tmp_path, SPAN_IN_JIT, rule="span-hygiene")
    assert len(found) == 1
    assert "times the trace" in found[0].message


def test_span_hygiene_span_in_lax_body_caught(tmp_path):
    found = lint(tmp_path, SPAN_IN_LAX_BODY, rule="span-hygiene")
    assert len(found) == 1 and found[0].severity == "error"


def test_span_hygiene_traced_decorator_on_jitted_caught(tmp_path):
    found = lint(tmp_path, TRACED_DECORATOR_ON_JITTED,
                 rule="span-hygiene")
    assert found and any("decorate the host-side caller" in f.message
                         for f in found)


def test_span_hygiene_traced_attr_in_callback_caught(tmp_path):
    """An io_callback target runs on the host and may span freely — but
    a span attribute reading a value traced in the ENCLOSING scope is a
    silent device sync at emit time."""
    found = lint(tmp_path, SPAN_READS_TRACED_VALUE, rule="span-hygiene")
    assert len(found) == 1
    assert "traced value" in found[0].message


def test_span_hygiene_host_and_callback_spans_clean(tmp_path):
    assert lint(tmp_path, SPAN_ON_HOST_CLEAN, rule="span-hygiene") == []
    assert lint(tmp_path, SPAN_IN_CALLBACK_CLEAN,
                rule="span-hygiene") == []


UNRELATED_SPAN_METHOD = """
import re
import jax

@jax.jit
def step(w, names):
    # trace-time host work: re.Match.span() is NOT the tracing API —
    # the rule must key on the tracing receiver / string phase arg
    m = re.match(r"w(\\d+)", "w3")
    lo, hi = m.span()
    spans = [m.span(0)]
    return w[lo:hi]
"""


def test_span_hygiene_ignores_unrelated_span_methods(tmp_path):
    assert lint(tmp_path, UNRELATED_SPAN_METHOD,
                rule="span-hygiene") == []


# --- overlap-hygiene rule ---------------------------------------------------

ASYNC_IN_JIT = """
import jax
from cocoa_tpu.parallel.distributed import async_host_allgather_bytes

@jax.jit
def step(w):
    h = async_host_allgather_bytes("dw", w)   # traced value escapes
    return w
"""

ASYNC_IN_LAX_BODY = """
from jax import lax
from cocoa_tpu.parallel import distributed

def run(w):
    def body(i, w):
        distributed.async_kv_get(None, "k")
        return w
    return lax.fori_loop(0, 3, body, w)
"""

HANDLE_NEVER_JOINED = """
from cocoa_tpu.parallel.distributed import async_host_allgather_bytes

def round_exchange(payload, dispatch):
    h = async_host_allgather_bytes("dw", payload)
    dispatch()          # the super-block crosses an un-joined exchange
    return None
"""

HANDLE_JOINED = """
from cocoa_tpu.parallel.distributed import async_host_allgather_bytes

def round_exchange(payload, dispatch):
    h = async_host_allgather_bytes("dw", payload)
    dispatch()
    return h.join()     # joined at the barrier: clean
"""

HANDLE_ESCAPES = """
from cocoa_tpu.parallel.distributed import async_host_allgather_bytes

def round_exchange(payload, window, t):
    h = async_host_allgather_bytes(f"dw{t}", payload)
    window.admit(t, h)  # handed to the join window: its job to join
"""


def test_overlap_hygiene_async_launch_in_jit_caught(tmp_path):
    found = lint(tmp_path, ASYNC_IN_JIT, rule="overlap-hygiene")
    assert len(found) == 1 and "exchange thread" in found[0].message


def test_overlap_hygiene_async_launch_in_lax_body_caught(tmp_path):
    found = lint(tmp_path, ASYNC_IN_LAX_BODY, rule="overlap-hygiene")
    assert len(found) == 1


def test_overlap_hygiene_unjoined_handle_caught(tmp_path):
    found = lint(tmp_path, HANDLE_NEVER_JOINED, rule="overlap-hygiene")
    assert len(found) == 1 and "never joined" in found[0].message


def test_overlap_hygiene_joined_or_escaping_clean(tmp_path):
    assert lint(tmp_path, HANDLE_JOINED, rule="overlap-hygiene") == []
    assert lint(tmp_path, HANDLE_ESCAPES, rule="overlap-hygiene") == []


# --- fleet-hygiene rule -----------------------------------------------------

TENANT_LOOP_IN_JIT = """
import jax
import jax.numpy as jnp

@jax.jit
def fleet_round(states, tables, n_tenants):
    # the anti-pattern the fleet path replaces: T kernels unrolled into
    # one graph, one compiled round PER TENANT
    out = []
    for t in range(n_tenants):
        out.append(states[t] + tables[t])
    return jnp.stack(out)

@jax.jit
def fleet_round2(tenants, tables):
    acc = jnp.zeros_like(tables[0])
    for tenant in tenants:
        acc = acc + tenant
    return acc
"""

TENANT_LOOP_IN_LAX_BODY = """
import jax.numpy as jnp
from jax import lax

def drive(state, tenants):
    def body(i, s):
        for tenant in tenants:
            s = s + tenant
        return s
    return lax.fori_loop(0, 10, body, state)
"""

TENANT_FETCH_IN_HOST_LOOP = """
import numpy as np

def report(fleet_w, tenants):
    out = []
    for t, tenant in enumerate(tenants):
        out.append(float(np.asarray(fleet_w[t])[0]))  # T d2h round-trips
    return out
"""

TENANT_LOOP_CLEAN = """
import jax
import numpy as np

def fleet_kernel(chunk_kernel, states):
    return jax.vmap(chunk_kernel)(states)   # the tenant axis rides vmap

def report(fleet_w, tenants):
    w_host = np.asarray(fleet_w)            # ONE fetch before the loop
    return [float(w_host[t, 0]) for t, tenant in enumerate(tenants)]
"""


def test_fleet_hygiene_tenant_loop_in_jit_caught(tmp_path):
    found = lint(tmp_path, TENANT_LOOP_IN_JIT, rule="fleet-hygiene")
    assert len(found) == 2 and all("unrolls" in f.message for f in found)


def test_fleet_hygiene_tenant_loop_in_lax_body_caught(tmp_path):
    found = lint(tmp_path, TENANT_LOOP_IN_LAX_BODY, rule="fleet-hygiene")
    assert len(found) == 1


def test_fleet_hygiene_per_tenant_fetch_caught(tmp_path):
    found = lint(tmp_path, TENANT_FETCH_IN_HOST_LOOP, rule="fleet-hygiene")
    assert len(found) == 1 and "ONCE before the loop" in found[0].message


def test_fleet_hygiene_vmap_and_prefetched_loop_clean(tmp_path):
    assert lint(tmp_path, TENANT_LOOP_CLEAN, rule="fleet-hygiene") == []


def test_fleet_hygiene_full_tree_clean():
    """The real tree carries ZERO fleet-hygiene findings — the rule's
    contract is that the shipped fleet path itself is the reference
    implementation of its own hygiene."""
    root = core.repo_root()
    sources = {}
    for rel in core.iter_py_files(root):
        src = core.load_source(root, rel)
        if src is not None:
            sources[src.path] = src
    found = [f for f in rules.run_static_rules(sources)
             if f.rule == "fleet-hygiene"]
    core.fingerprint_findings(found, sources)
    core.apply_suppressions(found, sources)
    assert [f for f in found if f.actionable] == []


# --- fingerprints / baseline / report --------------------------------------


def test_fingerprints_survive_unrelated_edits(tmp_path):
    found1 = lint(tmp_path, PR2_SHAPE, relpath="a.py")
    shifted = PR2_SHAPE.replace(
        "import functools", "# an unrelated comment\nimport functools")
    found2 = lint(tmp_path, shifted, relpath="a.py")
    fp1 = {f.fingerprint for f in found1}
    fp2 = {f.fingerprint for f in found2}
    assert fp1 == fp2 and fp1


def test_baseline_roundtrip(tmp_path):
    ab = tmp_path / "a.py"
    ab.write_text(PR2_SHAPE)
    src = core.load_source(str(tmp_path), "a.py")
    sources = {src.path: src}
    findings = rules.run_static_rules(sources)
    core.fingerprint_findings(findings, sources)
    bl_path = str(tmp_path / "baseline.json")
    core.write_baseline(findings, bl_path)
    bl = core.load_baseline(bl_path)
    stale = core.apply_baseline(findings, bl)
    assert stale == []
    assert all(f.baselined and not f.actionable for f in findings)
    # fixing the finding leaves a stale entry behind
    stale2 = core.apply_baseline([], bl)
    assert len(stale2) == len(bl)


def test_scoped_run_keeps_out_of_scope_baseline(tmp_path):
    """A targeted run (explicit path subset) must treat baseline entries
    for unscanned files as out-of-scope — not stale — and a path-scoped
    --update-baseline must carry them over untouched instead of wiping
    the repo's justified baseline."""
    findings, sources, stale = analysis.run_analysis(
        targets=["cocoa_tpu/solvers"], with_budget_checks=False)
    assert stale == [], [e["fingerprint"] for e in stale]
    # path-scoped rewrite: out-of-scope entries survive verbatim
    before = core.load_baseline()
    assert before, "repo baseline expected to be non-empty"
    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps(
        {"entries": list(before.values())}))
    core.write_baseline(
        [f for f in findings if not f.suppressed], str(bl),
        scanned_paths=set(sources))
    after = core.load_baseline(str(bl))
    assert after == before


def test_compile_bridge_survives_watch_teardown(tmp_path):
    """install_compile_events during an open watch_compiles context must
    keep counting after the context exits (the watch teardown must not
    restore the logger level out from under the process-lifetime
    bridge)."""
    if sanitize._BUS_BRIDGE is None:
        bus = tele.get_bus()
        bus.configure(jsonl_path=str(tmp_path / "ev.jsonl"))
        bus.reset()
    assert sanitize._BUS_BRIDGE is not None
    with sanitize.watch_compiles():
        pass
    seen = []
    bus = tele.get_bus()
    bus.subscribe(seen.append)
    try:
        jax.jit(lambda x: x * 3.5)(jnp.float32(2.0)).block_until_ready()
    finally:
        bus.reset()
    assert any(e.get("event") == "compile" for e in seen), seen


def test_report_jsonl_validates_against_schema(tmp_path):
    findings = lint(tmp_path, PR2_SHAPE, relpath="a.py")
    report = tmp_path / "report.jsonl"
    core.write_report(str(report), findings, files_scanned=1,
                      rules=analysis.RULES)
    assert schema.check_file(str(report)) == []
    # a corrupted finding line must trip the checker
    lines = report.read_text().splitlines()
    bad = json.loads(lines[1])
    del bad["fingerprint"]
    bad["severity"] = "catastrophic"
    report.write_text("\n".join([lines[0], json.dumps(bad)]) + "\n")
    errs = schema.check_file(str(report))
    assert any("fingerprint" in e for e in errs)
    assert any("catastrophic" in e for e in errs)


def test_repo_is_lint_clean():
    """The acceptance gate: `python -m cocoa_tpu.analysis` exits clean on
    this tree — every finding fixed, inline-justified, or baselined with
    a justification (never a TODO placeholder)."""
    findings, _, stale = analysis.run_analysis()
    new = [f for f in findings if f.actionable]
    assert new == [], [f"{f.location()}: {f.message}" for f in new]
    assert stale == [], stale
    for f in findings:
        if f.baselined:
            assert f.justification and "TODO" not in f.justification, \
                f.location()


# --- dynamic sanitizer on the CPU drive loop --------------------------------


@pytest.fixture()
def small_ds(tiny_data):
    return shard_dataset(tiny_data, k=K, layout="dense", dtype=jnp.float32)


_PARAMS = dict(num_rounds=12, lam=0.01, local_iters=15, beta=1.0, gamma=1.0)
_DBG = DebugParams(debug_iter=4, seed=0)


def test_transfer_guard_has_teeth():
    """An un-sanctioned scalar sync under the strict guard raises — the
    'zero unintended transfers' assertion is not vacuous.  (On CPU the
    device→host half of ``float(x[i])`` is zero-copy; what trips is the
    host→device upload of the index constant — on TPU both halves do.)"""
    x = jax.device_put(jnp.arange(3.0))
    with pytest.raises(Exception, match="[Dd]isallowed.*transfer"):
        with sanitize.no_transfers():
            float(x[0])
    # the sanctioned path through intended_fetch stays open
    with sanitize.no_transfers():
        with sanitize.intended_fetch("test"):
            assert float(x[0]) == 0.0


def test_sanitizer_drive_loop_compile_once_and_no_syncs(small_ds, tiny_data):
    """THE sanitizer contract (ISSUE 6 acceptance): the device-resident
    drive loop compiles exactly once per config, performs zero unintended
    device→host transfers inside the round loop, and a second identical
    run reuses the executable (zero compiles)."""
    params = Params(n=tiny_data.n, **_PARAMS)
    with sanitize.sanitizer() as s1:
        w1, a1, traj1 = run_cocoa(small_ds, params, _DBG, plus=True,
                                  quiet=True, device_loop=True)
    assert s1.compile_count("run") == 1, [c.name for c in s1.compiles]
    assert s1.intended_fetches >= 1
    with sanitize.sanitizer() as s2:
        w2, a2, traj2 = run_cocoa(small_ds, params, _DBG, plus=True,
                                  quiet=True, device_loop=True)
    assert s2.compiles == [], [c.name for c in s2.compiles]
    assert jnp.array_equal(w1, w2) and jnp.array_equal(a1, a2)
    assert len(traj2.records) == len(traj1.records)


def test_sanitizer_drive_loop_telemetry_on(small_ds, tiny_data, tmp_path):
    """Same invariants with every telemetry sink attached: the
    io_callback tap must not introduce unintended transfers, and the
    metrics textfile exposes compiles_total / host_transfers_total."""
    params = Params(n=tiny_data.n, **_PARAMS)
    ev = str(tmp_path / "events.jsonl")
    mp = str(tmp_path / "metrics.prom")
    bus = tele.get_bus()
    bus.configure(jsonl_path=ev, metrics_path=mp)
    try:
        with sanitize.sanitizer() as s:
            w, a, _ = run_cocoa(small_ds, params, _DBG, plus=True,
                                quiet=True, device_loop=True)
        assert s.compile_count("run") <= 1
        assert s.intended_fetches >= 1
    finally:
        bus.reset()
    # telemetry-off reference run must match bit-for-bit
    w0, a0, _ = run_cocoa(small_ds, params, _DBG, plus=True, quiet=True,
                          device_loop=True)
    assert jnp.array_equal(w, w0) and jnp.array_equal(a, a0)
    assert schema.check_file(ev) == []
    evs = [json.loads(l) for l in open(ev)]
    kinds = {e["event"] for e in evs}
    assert "host_transfer" in kinds
    text = open(mp).read()
    assert "cocoa_compiles_total" in text
    assert "cocoa_host_transfers_total" in text
    ht = int([l for l in text.splitlines()
              if l.startswith("cocoa_host_transfers_total")][0].split()[1])
    assert ht == sum(1 for e in evs if e["event"] == "host_transfer")


def test_host_stepped_eval_fetch_is_sanctioned(small_ds, tiny_data):
    """The chunked (host-stepped) driver's per-eval fetch rides
    intended_fetch too — the sanitizer passes on the scan_chunk path."""
    params = Params(n=tiny_data.n, **_PARAMS)
    with sanitize.sanitizer(strict="d2h") as s:
        w, a, traj = run_cocoa(small_ds, params, _DBG, plus=True,
                               quiet=True, scan_chunk=4)
    assert s.intended_fetches >= len(traj.records)


def test_metrics_writer_counts_sanitizer_events(tmp_path):
    from cocoa_tpu.telemetry.metrics import MetricsWriter

    mp = str(tmp_path / "m.prom")
    w = MetricsWriter(mp)
    w({"event": "compile", "name": "run", "seconds": 0.5, "ts": 1.0})
    w({"event": "compile", "name": "eval", "seconds": 0.1, "ts": 2.0})
    w({"event": "host_transfer", "label": "device_loop_fetch", "ts": 3.0})
    text = open(mp).read()
    assert "cocoa_compiles_total 2" in text
    assert "cocoa_host_transfers_total 1" in text


def test_analysis_cli_exits_clean(tmp_path):
    """`python -m cocoa_tpu.analysis` (the CI gate) exits 0 on this tree
    and writes a schema-valid report."""
    from cocoa_tpu.analysis.__main__ import main

    report = str(tmp_path / "report.jsonl")
    rc = main([f"--report={report}"])
    assert rc == 0
    assert schema.check_file(report) == []


# --- serve-hygiene rule ------------------------------------------------------

SERVE_JIT_IN_HOT_PATH = """
import jax

def score_batch(w, idx, val):
    fn = jax.jit(lambda w, i, v: (w[i] * v).sum(-1))
    return fn(w, idx, val)
"""

SERVE_LEN_SHAPE = """
import numpy as np

def drain_requests(requests, width):
    idx = np.zeros((len(requests), width), np.int32)
    return idx
"""

SERVE_CLOCK_IN_TRACED = """
import time
import jax

@jax.jit
def serve_margins(w, idx, val):
    t0 = time.monotonic()
    return (w[idx] * val).sum(-1)
"""

SERVE_SYNC_IN_TRACED = """
import jax

@jax.jit
def serve_margins(w, idx, val):
    out = (w[idx] * val).sum(-1)
    out.block_until_ready()
    return out
"""

SERVE_CLEAN = """
import time
import jax
import numpy as np

class Scorer:
    def __init__(self):
        # builder scope: the one sanctioned place to create the jit
        self._jit = jax.jit(lambda w, i, v: (w[i] * v).sum(-1))

    def assemble(self, queries, bucket, width):
        # static bucket shape, never len(queries)
        idx = np.zeros((bucket, width), np.int32)
        return idx

    def score(self, w, idx, val):
        t0 = time.monotonic()   # host boundary: clocks are fine here
        return self._jit(w, idx, val)
"""


def test_serve_hygiene_jit_in_hot_path_caught(tmp_path):
    found = lint(tmp_path, SERVE_JIT_IN_HOT_PATH,
                 relpath="cocoa_tpu/serving/fixture.py",
                 rule="serve-hygiene")
    assert len(found) == 1 and "fresh" in found[0].message


def test_serve_hygiene_request_dependent_shape_caught(tmp_path):
    found = lint(tmp_path, SERVE_LEN_SHAPE,
                 relpath="cocoa_tpu/serving/fixture.py",
                 rule="serve-hygiene")
    assert len(found) == 1
    assert "static bucket" in found[0].message


def test_serve_hygiene_clock_in_traced_caught(tmp_path):
    found = lint(tmp_path, SERVE_CLOCK_IN_TRACED,
                 relpath="cocoa_tpu/serving/fixture.py",
                 rule="serve-hygiene")
    assert len(found) == 1 and "TRACE time" in found[0].message


def test_serve_hygiene_device_sync_in_traced_caught(tmp_path):
    found = lint(tmp_path, SERVE_SYNC_IN_TRACED,
                 relpath="cocoa_tpu/serving/fixture.py",
                 rule="serve-hygiene")
    assert len(found) == 1 and "block_until_ready" in found[0].message


def test_serve_hygiene_builder_scopes_clean(tmp_path):
    found = lint(tmp_path, SERVE_CLEAN,
                 relpath="cocoa_tpu/serving/fixture.py",
                 rule="serve-hygiene")
    assert found == []


def test_serve_hygiene_scoped_to_serving(tmp_path):
    # the same shapes OUTSIDE serving/ are not this rule's business
    # (host-sync and friends still apply on their own terms)
    found = lint(tmp_path, SERVE_JIT_IN_HOT_PATH,
                 relpath="cocoa_tpu/solvers/fixture.py",
                 rule="serve-hygiene")
    assert found == []


SERVE_QUANT_IN_TRACED = """
import jax
import jax.numpy as jnp

@jax.jit
def serve_margins(w, idx, val):
    scale = jnp.abs(w).max() / 127.0
    wq = (w / scale).astype(jnp.int8)
    return (wq[idx].astype(jnp.float32) * scale * val).sum(-1)
"""

SERVE_QUANT_ON_HOST = """
import jax
import jax.numpy as jnp
import numpy as np

def quantize(w):
    # host-side swap-time quantization: abs-max scale and a narrowing
    # cast are exactly where they belong (no jit anywhere near)
    scale = np.abs(w).max() / 127.0
    return (w / scale).astype(np.int8), scale

@jax.jit
def serve_margins(wq, scale, idx, val):
    # widening back to f32 on the gathered rows is the legal direction
    return (wq[idx].astype(jnp.float32) * scale * val).sum(-1)
"""


def test_serve_hygiene_quantize_in_traced_caught(tmp_path):
    found = lint(tmp_path, SERVE_QUANT_IN_TRACED,
                 relpath="cocoa_tpu/serving/fixture.py",
                 rule="serve-hygiene")
    # one finding per half of the in-graph quantize: the abs-max scale
    # and the narrowing cast (the widening astype(float32) stays clean)
    assert len(found) == 2, [(f.line, f.message) for f in found]
    assert any("max-of-abs" in f.message for f in found)
    assert any("astype(int8)" in f.message
               and "quantize ONCE on the host" in f.message
               for f in found)


def test_serve_hygiene_host_quantize_and_widening_clean(tmp_path):
    found = lint(tmp_path, SERVE_QUANT_ON_HOST,
                 relpath="cocoa_tpu/serving/fixture.py",
                 rule="serve-hygiene")
    assert found == [], [(f.line, f.message) for f in found]


def test_serve_hygiene_full_serving_tree_clean():
    """The shipped serving subsystem passes its own rule (and every
    other rule) with zero new findings."""
    findings, _, _ = analysis.run_analysis(
        targets=["cocoa_tpu/serving"], with_budget_checks=False)
    actionable = [f for f in findings if f.actionable]
    assert actionable == [], [(f.rule, f.path, f.line, f.message)
                              for f in actionable]
