"""Streaming sharded ingest (data/ingest.py, docs/DESIGN.md §12).

The contract under test: the two-pass byte-range pipeline — index scan +
shard-range parse — builds a ``ShardedDataset`` BIT-IDENTICAL to the
whole-file replicated builder for the same file/config, across layouts,
the hybrid hot/cold split, the dense eval twin, and multiplexed dp
meshes; and a streamed multiplexed 2-process run trains the identical
(w, α) trajectory as the single-process replicated control (the
acceptance pin for ISSUE 8, via the tests/_multihost_data.py pattern).
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

from conftest import SMALL_TRAIN, DEMO_NUM_FEATURES

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TESTS = os.path.dirname(os.path.abspath(__file__))


def _assert_ds_equal(ds_a, ds_b):
    """Bit-exact ShardedDataset equality: metadata + every shard array."""
    assert ds_a.layout == ds_b.layout
    assert ds_a.n == ds_b.n
    assert ds_a.num_features == ds_b.num_features
    np.testing.assert_array_equal(ds_a.counts, ds_b.counts)
    arrs_a, arrs_b = ds_a.shard_arrays(), ds_b.shard_arrays()
    assert arrs_a.keys() == arrs_b.keys()
    for f in arrs_a:
        a, b = np.asarray(arrs_a[f]), np.asarray(arrs_b[f])
        assert a.dtype == b.dtype, f
        assert a.shape == b.shape, f
        np.testing.assert_array_equal(a, b, err_msg=f)


def test_build_index_matches_whole_parse():
    from cocoa_tpu.data import build_index, load_libsvm

    d = DEMO_NUM_FEATURES
    data = load_libsvm(SMALL_TRAIN, d)
    index = build_index(SMALL_TRAIN, d)
    assert index.n == data.n
    assert index.total_nnz == int(data.indptr[-1])
    np.testing.assert_array_equal(index.row_nnz, np.diff(data.indptr))
    np.testing.assert_array_equal(
        index.hist, np.bincount(data.indices, minlength=d))
    # row_off is a strictly increasing line-start index ending at EOF
    assert index.row_off[0] == 0
    assert index.row_off[-1] == os.path.getsize(SMALL_TRAIN)
    assert (np.diff(index.row_off) > 0).all()


def test_build_index_window_size_invariant():
    """The pass-1 window is a memory bound, not a semantic knob: a tiny
    window that forces many range parses assembles the identical index."""
    from cocoa_tpu.data import build_index

    d = DEMO_NUM_FEATURES
    ref = build_index(SMALL_TRAIN, d)
    tiny = build_index(SMALL_TRAIN, d, window=10_000)
    np.testing.assert_array_equal(tiny.row_off, ref.row_off)
    np.testing.assert_array_equal(tiny.row_nnz, ref.row_nnz)
    np.testing.assert_array_equal(tiny.hist, ref.hist)


@pytest.mark.parametrize("layout", ["dense", "sparse"])
@pytest.mark.parametrize("k", [2, 4])
def test_stream_equals_whole(layout, k):
    import jax.numpy as jnp

    from cocoa_tpu.data import load_libsvm, shard_dataset, stream_shard_dataset

    d = DEMO_NUM_FEATURES
    data = load_libsvm(SMALL_TRAIN, d)
    ds_whole = shard_dataset(data, k=k, layout=layout, dtype=jnp.float32)
    ds_stream, info = stream_shard_dataset(
        SMALL_TRAIN, d, k, layout=layout, dtype=jnp.float32)
    _assert_ds_equal(ds_whole, ds_stream)
    # single-process pass 2 parses every row exactly once
    assert info.rows == data.n
    assert info.nnz == int(data.indptr[-1])
    assert info.bytes_read == os.path.getsize(SMALL_TRAIN)


def test_stream_equals_whole_hybrid_and_eval_twin():
    import jax.numpy as jnp

    from cocoa_tpu.data import load_libsvm, shard_dataset, stream_shard_dataset

    d = DEMO_NUM_FEATURES
    data = load_libsvm(SMALL_TRAIN, d)
    ds_whole = shard_dataset(data, k=2, layout="sparse", dtype=jnp.float32,
                             hot_cols=64, eval_dense=True)
    ds_stream, info = stream_shard_dataset(
        SMALL_TRAIN, d, 2, layout="sparse", dtype=jnp.float32,
        hot_cols=64, eval_dense=True)
    _assert_ds_equal(ds_whole, ds_stream)
    # the residual width is the measured global max cold nnz
    assert info.residual_max_nnz == ds_whole.sp_indices.shape[-1]


def test_stream_equals_whole_multiplexed_mesh():
    """Single-process multiplexed dp mesh (D=2 devices < K=4 shards):
    streamed build places exactly like the replicated builder."""
    import jax
    import jax.numpy as jnp

    from cocoa_tpu.data import load_libsvm, shard_dataset, stream_shard_dataset
    from cocoa_tpu.parallel import make_mesh

    if len(jax.devices()) < 2:
        pytest.skip("needs the virtual multi-device CPU backend")
    mesh = make_mesh(2)
    d = DEMO_NUM_FEATURES
    data = load_libsvm(SMALL_TRAIN, d)
    for layout in ("dense", "sparse"):
        ds_whole = shard_dataset(data, k=4, layout=layout,
                                 dtype=jnp.float32, mesh=mesh)
        ds_stream, _ = stream_shard_dataset(
            SMALL_TRAIN, d, 4, layout=layout, dtype=jnp.float32, mesh=mesh)
        _assert_ds_equal(ds_whole, ds_stream)


def test_stream_hot_width_resolution_matches_whole():
    """--hotCols resolution parity: the width/ids resolved from the pass-1
    histogram equal the whole-file resolution (same counts, same
    tie-breaks), for auto and explicit specs."""
    import jax.numpy as jnp

    from cocoa_tpu.data import load_libsvm
    from cocoa_tpu.data import hybrid as hybrid_lib
    from cocoa_tpu.data.ingest import build_index

    d = DEMO_NUM_FEATURES
    data = load_libsvm(SMALL_TRAIN, d)
    index = build_index(SMALL_TRAIN, d)
    k, dtype = 4, jnp.float32
    for spec in ("auto", "128", "64"):
        n_whole, _ = hybrid_lib.resolve_hot_cols(spec, data, k, dtype)
        n_stream = hybrid_lib.resolve_hot_width(spec, index.hist, data.n,
                                                k, dtype)
        assert n_whole == n_stream, spec
        if n_whole:
            np.testing.assert_array_equal(
                hybrid_lib.hottest_columns(index.hist, n_whole),
                hybrid_lib.hottest_columns(hybrid_lib.column_counts(data),
                                           n_whole))


def test_resolve_layout_stats_matches_data_resolution():
    from cocoa_tpu.data import load_libsvm
    from cocoa_tpu.data.sharding import resolve_layout, resolve_layout_stats

    d = DEMO_NUM_FEATURES
    data = load_libsvm(SMALL_TRAIN, d)
    for layout in ("auto", "dense", "sparse"):
        assert resolve_layout_stats(
            data.n, d, int(data.indptr[-1]), layout, None
        ) == resolve_layout(data, layout, None)


def test_resolve_ingest_mode():
    import jax

    from cocoa_tpu.data.ingest import resolve_ingest_mode
    from cocoa_tpu.parallel import make_mesh

    # single-process auto keeps the whole-file A/B control
    assert resolve_ingest_mode(None, None) == "whole"
    assert resolve_ingest_mode("auto", None) == "whole"
    assert resolve_ingest_mode("whole", None) == "whole"
    # --ingestCache armed: auto routes through the shard-granular
    # pipeline (what consults/populates the cache), explicit whole wins
    assert resolve_ingest_mode("auto", None, cached=True) == "stream"
    assert resolve_ingest_mode(None, None, cached=True) == "stream"
    assert resolve_ingest_mode("whole", None, cached=True) == "whole"
    assert resolve_ingest_mode("auto", None, objective="lasso",
                               cached=True) == "whole"
    # explicit stream is honored wherever it is legal
    assert resolve_ingest_mode("stream", None) == "stream"
    if len(jax.devices()) >= 2:
        assert resolve_ingest_mode("stream", make_mesh(2)) == "stream"
    with pytest.raises(ValueError, match="lasso"):
        resolve_ingest_mode("stream", None, objective="lasso")
    with pytest.raises(ValueError, match="ingest must be"):
        resolve_ingest_mode("shard", None)


def test_resolve_ingest_mode_rejects_fp_mesh():
    """fp meshes have no per-device byte range; stream must reject them
    loudly (auto falls back to whole)."""
    import jax
    from jax.sharding import Mesh

    from cocoa_tpu.data.ingest import resolve_ingest_mode

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 virtual devices")
    # plain Mesh construction (make_mesh's AxisType path needs newer jax)
    fp_mesh = Mesh(np.asarray(jax.devices()[:4]).reshape(2, 2),
                   ("dp", "fp"))
    with pytest.raises(ValueError, match="feature-parallel"):
        resolve_ingest_mode("stream", fp_mesh)
    assert resolve_ingest_mode("auto", fp_mesh) == "whole"
    # even with a cache armed, fp keeps whole (nothing shard-keyed)
    assert resolve_ingest_mode("auto", fp_mesh, cached=True) == "whole"


def test_stream_rejects_fp_mesh_and_bad_eval_dense(tmp_path):
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from cocoa_tpu.data import stream_shard_dataset

    with pytest.raises(ValueError, match="eval_dense"):
        stream_shard_dataset(SMALL_TRAIN, DEMO_NUM_FEATURES, 2,
                             layout="dense", dtype=jnp.float32,
                             eval_dense=True)
    if len(jax.devices()) >= 4:
        fp_mesh = Mesh(np.asarray(jax.devices()[:4]).reshape(2, 2),
                       ("dp", "fp"))
        with pytest.raises(ValueError, match="feature-parallel"):
            stream_shard_dataset(SMALL_TRAIN, DEMO_NUM_FEATURES, 2,
                                 dtype=jnp.float32, mesh=fp_mesh)


def test_stream_detects_file_change(tmp_path):
    """A file rewritten between pass 1 and pass 2 must fail loudly, not
    train on silently skewed shards."""
    import jax.numpy as jnp

    from cocoa_tpu.data.ingest import build_index, stream_shard_dataset

    path = tmp_path / "mut.svm"
    path.write_text("1 1:1.0\n-1 2:2.0\n1 3:3.0\n-1 1:4.0\n")
    index = build_index(str(path), 10)
    path.write_text("1 1:1.0 2:2.0 3:3.0 4:4.0\n" * 4)
    with pytest.raises(ValueError, match="changed during ingest"):
        stream_shard_dataset(str(path), 10, 2, layout="sparse",
                             dtype=jnp.float32, index=index)


# --- the acceptance pin: 2-process streamed multiplexed ≡ replicated ------
#
# Two halves, because this container's jax (0.4.37) cannot run jit
# computations over a multi-process CPU mesh at all (the same known
# limitation that fails tests/test_multihost.py's solver runs on the
# seed — "Multiprocess computations aren't implemented on the CPU
# backend"):
#
# 1. REAL 2-process build (subprocess workers over jax.distributed/Gloo,
#    one device each, K=4 multiplexing m=2 per device): every worker
#    streams ONLY its own shards' byte ranges and the assembled global
#    dataset's shard arrays are bit-identical to the single-process
#    replicated control — hybrid split on and off.
# 2. The (w, α) TRAJECTORY pin runs on the simulated multi-host backend
#    (the virtual multi-device CPU mesh, same shard_map/psum code path
#    as a real pod): the streamed multiplexed build trains bit-identically
#    to the whole-file build on the same mesh, and matches the replicated
#    no-mesh control at the f64 reduction-order tolerance the repo's
#    multiplexing suite pins (tests/test_multiplex.py).
#
# Together: streamed build ≡ control build (bit-exact, real processes) and
# control-equal builds train identically — the end-to-end 2-process run is
# CI's streamed-multiplexed smoke once the backend supports it.

_WORKER = r"""
import json, os, sys
proc_id, nproc, port, path, outdir = (
    int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4],
    sys.argv[5])
os.environ.pop("JAX_PLATFORMS", None)
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

from cocoa_tpu.parallel.distributed import maybe_initialize
assert maybe_initialize(f"127.0.0.1:{port}", process_id=proc_id,
                        num_processes=nproc)

import jax.numpy as jnp
import numpy as np
from _multihost_data import D
from cocoa_tpu.data.ingest import build_index, stream_shard_dataset
from cocoa_tpu.parallel import make_mesh

assert len(jax.devices()) == nproc  # one CPU device per process
mesh = make_mesh(nproc)
K = 4  # m = K/D = 2 logical shards multiplex per device

index = build_index(path, D)
out = {}
for tag, hot in (("plain", 0), ("hybrid", 8)):
    ds, info = stream_shard_dataset(
        path, D, K, layout="sparse", dtype=jnp.float64, mesh=mesh,
        hot_cols=hot, index=index)
    # pass 2 parsed ONLY this process's rows — the streaming guarantee
    assert info.rows < index.n, (tag, info.rows, index.n)
    out[f"{tag}|rows"] = np.asarray([info.rows])
    for field, arr in ds.shard_arrays().items():
        for s in arr.addressable_shards:
            lo = s.index[0].start or 0
            out[f"{tag}|{field}|{lo}"] = np.asarray(s.data)
np.savez(os.path.join(outdir, f"worker{proc_id}.npz"), **out)
print("WORKER_DONE", flush=True)
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow
def test_two_process_streamed_multiplexed_build_matches_control(tmp_path):
    from _multihost_data import write_libsvm

    data = write_libsvm(tmp_path / "mh.svm")
    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER)
    port = _free_port()
    env = {**os.environ, "PYTHONPATH": f"{ROOT}{os.pathsep}{TESTS}"}
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "host_platform_device_count" not in f
    )
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(i), "2", str(port),
             str(tmp_path / "mh.svm"), str(tmp_path)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
            cwd=ROOT, text=True,
        )
        for i in range(2)
    ]
    try:
        for p in procs:
            out, _ = p.communicate(timeout=220)
            assert p.returncode == 0, f"worker failed:\n{out[-3000:]}"
            assert "WORKER_DONE" in out
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()

    results = [dict(np.load(tmp_path / f"worker{i}.npz")) for i in (0, 1)]

    # each process streamed a strict subset; together they tile the file
    import jax.numpy as jnp

    from cocoa_tpu.data.sharding import shard_dataset

    for tag, hot in (("plain", 0), ("hybrid", 8)):
        rows = [int(res[f"{tag}|rows"][0]) for res in results]
        assert all(r < data.n for r in rows)
        assert sum(rows) == data.n

        # the 2-process assembled shard arrays tile the control's exactly
        ds = shard_dataset(data, k=4, layout="sparse", dtype=jnp.float64,
                           hot_cols=hot)
        for field, ctrl in ds.shard_arrays().items():
            ctrl = np.asarray(ctrl)
            seen = 0
            for res in results:
                for key, val in res.items():
                    if key.startswith(f"{tag}|{field}|"):
                        lo = int(key.rsplit("|", 1)[1])
                        assert val.dtype == ctrl.dtype, (tag, field)
                        np.testing.assert_array_equal(
                            val, ctrl[lo:lo + val.shape[0]],
                            err_msg=f"{tag}: {field}[{lo}]")
                        seen += val.shape[0]
            assert seen == 4, (tag, field)  # every shard exactly once


@pytest.mark.slow
def test_streamed_multiplexed_trajectory_matches_replicated_control(
        tmp_path):
    """The (w, α) pin on the simulated multi-host backend: streamed
    multiplexed (D=2 virtual devices < K=4 shards) trains BIT-IDENTICALLY
    to the whole-file build on the same mesh — and both match the
    replicated no-mesh control at the f64 reduction-order tolerance the
    multiplexing suite pins (the psum tree differs between topologies,
    tests/test_multiplex.py)."""
    import jax
    import jax.numpy as jnp

    from _multihost_data import D, write_libsvm
    from cocoa_tpu.config import DebugParams, Params
    from cocoa_tpu.data.ingest import stream_shard_dataset
    from cocoa_tpu.data.sharding import shard_dataset
    from cocoa_tpu.parallel import make_mesh
    from cocoa_tpu.solvers import run_cocoa

    data = write_libsvm(tmp_path / "mh.svm")
    params = Params(n=data.n, num_rounds=5, local_iters=10, lam=0.01)
    # the multiplexed shard_map path needs newer jax; the replicated vmap
    # arm below still pins streamed-vs-whole trajectory bit-identity here
    mesh = (make_mesh(2) if len(jax.devices()) >= 2
            and hasattr(jax, "shard_map") else None)

    def train(ds, mesh):
        w, alpha, traj = run_cocoa(ds, params,
                                   DebugParams(debug_iter=1, seed=0),
                                   plus=True, mesh=mesh, quiet=True)
        return (np.asarray(w), np.asarray(alpha),
                np.asarray([r.gap for r in traj.records]))

    for hot in (0, 8):
        ctrl = train(shard_dataset(data, k=4, layout="sparse",
                                   dtype=jnp.float64, hot_cols=hot), None)

        # streamed replicated build (no mesh): bit-identical to the
        # whole-file control — same arrays in, same vmap path
        ds_flat, _ = stream_shard_dataset(
            str(tmp_path / "mh.svm"), D, 4, layout="sparse",
            dtype=jnp.float64, hot_cols=hot)
        flat = train(ds_flat, None)
        for g, x, name in zip(flat, ctrl, ("w", "alpha", "gaps")):
            np.testing.assert_array_equal(g, x,
                                          err_msg=f"hot={hot}: {name}")

        if mesh is None:
            continue
        ds_stream, _ = stream_shard_dataset(
            str(tmp_path / "mh.svm"), D, 4, layout="sparse",
            dtype=jnp.float64, mesh=mesh, hot_cols=hot)
        ds_whole = shard_dataset(data, k=4, layout="sparse",
                                 dtype=jnp.float64, mesh=mesh,
                                 hot_cols=hot)
        got = train(ds_stream, mesh)
        want = train(ds_whole, mesh)
        for g, x, name in zip(got, want, ("w", "alpha", "gaps")):
            np.testing.assert_array_equal(g, x,
                                          err_msg=f"hot={hot}: {name}")
        for g, x, name in zip(got, ctrl, ("w", "alpha", "gaps")):
            np.testing.assert_allclose(g, x, atol=1e-12,
                                       err_msg=f"hot={hot}: {name}")
