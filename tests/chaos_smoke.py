"""CI chaos smoke: a real 2-process gang, an injected SIGKILL, shrink to
the survivor, schema-validated events and gang gauges.

Not a pytest file (no ``test_`` prefix): run it directly —

    PYTHONPATH=.:tests python tests/chaos_smoke.py <artifact-dir>

It supervises the real-process toy gang (tests/_gang_worker.py: real
jax.distributed rendezvous + per-round KV allgather + real checkpoints)
with a deterministic kill from tests/_faults.py, requires the supervisor
to reform the gang at P′=1 and the survivor to finish bit-identically to
an unfailed 2-process control, then validates the emitted event JSONL
with the shared schema checker and greps the gang gauges out of the
metrics textfile.  Exit code 0 = every check held.  The same scenario is
pinned as tests (tests/test_chaos.py); this script keeps it visible as
its own CI signal with uploadable artifacts.
"""

import json
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

from _faults import Fault, FaultPlan, checkpoint_at_least, sigkill
from cocoa_tpu import checkpoint as ckpt_lib
from cocoa_tpu import elastic
from cocoa_tpu.telemetry import events as tele_events
from cocoa_tpu.telemetry import schema as tele_schema


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    outdir = argv[0] if argv else tempfile.mkdtemp(prefix="chaos-smoke-")
    os.makedirs(outdir, exist_ok=True)
    events_path = os.path.join(outdir, "chaos-events.jsonl")
    metrics_path = os.path.join(outdir, "chaos-metrics.prom")
    workdir = tempfile.mkdtemp(prefix="chaos-gang-")
    ck = os.path.join(workdir, "ck")
    ck_ref = os.path.join(workdir, "ck_ref")

    env_pp = os.environ.get("PYTHONPATH", "")
    os.environ["PYTHONPATH"] = os.pathsep.join(
        [p for p in (os.path.dirname(os.path.abspath(__file__)),
                     os.path.dirname(os.path.dirname(
                         os.path.abspath(__file__))), env_pp) if p])

    from cocoa_tpu.telemetry.metrics import MetricsWriter

    bus = tele_events.get_bus()
    bus.configure(jsonl_path=events_path)
    bus.subscribe(MetricsWriter(metrics_path, families="gang"))

    def toy_argv(ckdir, telemetry=False):
        argv = [f"--chkptDir={ckdir}", "--numSplits=4", "--numRounds=20",
                "--chkptIter=5", "--stepSeconds=0.05"]
        if telemetry:
            # workers stream events + spans per process (worker 0 shares
            # the supervisor's file, worker 1 writes `.p1`) so the kill
            # leaves a flight-recorder artifact and trace_report has a
            # real gang timeline to assemble
            argv += [f"--events={events_path}", "--trace"]
        return argv

    plan = FaultPlan(
        Fault(generation=0, actions=(sigkill(1),),
              trigger=checkpoint_at_least(ck, "ToyGang", 5),
              name="kill-worker-1"),
    )
    print("chaos-smoke: 2-process gang, SIGKILL worker 1 mid-run, "
          "shrink to the survivor", flush=True)
    rc = elastic.supervise(
        toy_argv(ck, telemetry=True), 2, module="_gang_worker",
        max_restarts=3,
        poll_s=0.05, num_splits=4, shrink="now", backoff_base_s=0.2,
        on_generation=plan.on_generation,
    )
    plan.join()
    failures = []
    if rc != 0:
        failures.append(f"supervised run exited {rc}")
    if plan.errors:
        failures.append(f"fault plan errors: {plan.errors}")
    if plan.fired != ["kill-worker-1"]:
        failures.append(f"fault never fired: {plan.fired}")

    path = ckpt_lib.latest(ck, "ToyGang")
    meta = w = None
    if path is None:
        failures.append("no final checkpoint from the survived run")
    else:
        meta, w, _ = ckpt_lib.load(path)
        if meta["round"] != 20:
            failures.append(f"survivor stopped at round {meta['round']}")

    print("chaos-smoke: unfailed 2-process control", flush=True)
    rc_ref = elastic.supervise(toy_argv(ck_ref), 2, module="_gang_worker",
                               max_restarts=0, poll_s=0.05)
    if rc_ref != 0:
        failures.append(f"control run exited {rc_ref}")
    else:
        _, w_ref, _ = ckpt_lib.load(ckpt_lib.latest(ck_ref, "ToyGang"))
        if w is not None and not np.array_equal(w, w_ref):
            failures.append("survived run != unfailed control (the shrink "
                            "bit-identity contract broke)")

    errs = tele_schema.check_file(events_path)
    if errs:
        failures.append(f"events schema violations: {errs[:5]}")
    recs = [json.loads(ln) for ln in open(events_path)]
    if not any(r["event"] == "gang_resize" and r["new_size"] == 1
               for r in recs):
        failures.append("no gang_resize event to P'=1 in the stream")
    metrics_text = open(metrics_path).read()
    for needle in ("cocoa_gang_size 1", "cocoa_gang_generations_total"):
        if needle not in metrics_text:
            failures.append(f"metrics textfile lacks {needle!r}")

    # the crash flight recorder (ISSUE 10): the SIGKILLed worker 1's
    # last-N events, dumped by the supervisor from the victim's stream
    frec = events_path + ".p1.flightrec"
    if not os.path.exists(frec):
        failures.append(f"no flight-recorder dump at {frec}")
    else:
        errs = tele_schema.check_file(frec)
        if errs:
            failures.append(f"flightrec schema violations: {errs[:5]}")
        frecs = [json.loads(ln) for ln in open(frec)]
        man = frecs[0].get("flightrec_manifest", {})
        if man.get("reason") != "worker_died" or len(frecs) < 2:
            failures.append(f"flightrec manifest wrong: {man}")

    # the span streams assemble into a schema-valid Perfetto trace with
    # a nonempty per-round critical path and a worker x phase straggler
    # table (telemetry/trace_report.py)
    from cocoa_tpu.telemetry import trace_report

    streams = [p for p in (events_path, events_path + ".p1")
               if os.path.exists(p)]
    spans = trace_report.load_spans(streams)
    if not spans:
        failures.append("no spans in the gang's event streams")
    else:
        trace = trace_report.chrome_trace(spans)
        terrs = trace_report.check_chrome_trace(trace)
        if terrs:
            failures.append(f"chrome trace invalid: {terrs[:5]}")
        with open(os.path.join(outdir, "chaos-trace.json"), "w") as f:
            json.dump(trace, f)
        cpath = trace_report.critical_path(spans)
        if not cpath:
            failures.append("empty per-round critical path")
        rows = trace_report.stragglers(spans)
        if not rows:
            failures.append("empty straggler table")
        else:
            top = rows[0]
            print(f"chaos-smoke: top straggler worker {top['worker']} x "
                  f"{top['phase']} (slack {top['slack_s']:.4f}s)",
                  flush=True)

    failures += overlap_smoke(outdir, workdir)
    failures += ingest_cache_smoke(outdir, workdir)

    if failures:
        for f in failures:
            print(f"chaos-smoke FAIL: {f}", file=sys.stderr)
        return 1
    print("chaos-smoke: OK — kill survived, gang shrunk 2->1, final "
          "state bit-identical to the control, events schema-valid, "
          "gang gauges present, flightrec dumped, trace assembled, "
          "overlap+staleness cut the exchange slack, restarted "
          "generation re-ingested from the slab cache with zero "
          "re-parsed bytes", flush=True)
    return 0


def overlap_smoke(outdir: str, workdir: str) -> list:
    """The ISSUE-12 chaos-step variant: the deterministic rotating
    `--stepSkew` REAL-math gang (tests/_gang_worker.py --real=cocoa),
    synchronous control vs `--overlapComm=on --staleRounds=1`.  Both
    must certify the 1e-4 gap; the treatment's exchange-phase
    straggler slack (telemetry/trace_report.py) must drop >= 40%; the
    treatment stream must schema-validate and carry the typed
    comm_overlap/stale_join events; the slack gauges land in
    `overlap-straggler.prom` for the CI grep."""
    from _gang_worker import EXCHANGE_PHASES, supervise_gang
    from cocoa_tpu.telemetry import schema as _schema
    from cocoa_tpu.telemetry import trace_report

    failures = []
    exchange_phases = EXCHANGE_PHASES
    base = ["--real=cocoa", "--numSplits=2", "--numRounds=400",
            "--debugIter=10", "--gapTarget=1e-4", "--lambda=0.01",
            "--rowsPerShard=64", "--numFeatures=32", "--localIters=16",
            "--trace", "--stepSeconds=0.008", "--stepSkew=0.03",
            "--skewEvery=2"]

    def run(name, levers):
        ev = os.path.join(workdir, f"overlap-{name}.jsonl")
        rc, recs = supervise_gang(base + list(levers), events=ev)
        if rc != 0:
            failures.append(f"overlap {name} gang exited {rc}")
            return None, None
        ends = [r for r in recs if r["event"] == "run_end"]
        if not ends or ends[-1].get("stopped") != "target":
            failures.append(f"overlap {name} run did not certify")
        spans = trace_report.load_spans([ev, ev + ".p1"])
        rows = trace_report.stragglers(spans)
        slack = sum(r["slack_s"] for r in rows
                    if r["phase"] in exchange_phases)
        return slack, (ev, recs, spans)

    print("chaos-smoke: skewed real-math gang, synchronous control",
          flush=True)
    ctl_slack, _ = run("control", ["--overlapComm=off",
                                   "--staleRounds=0"])
    print("chaos-smoke: skewed real-math gang, overlap + staleness",
          flush=True)
    trt_slack, trt = run("treatment", ["--overlapComm=on",
                                       "--staleRounds=1"])
    if ctl_slack is None or trt_slack is None:
        return failures
    if ctl_slack <= 0.5:
        failures.append(f"control exchange slack too small to A/B "
                        f"({ctl_slack:.3f}s)")
    elif trt_slack > 0.6 * ctl_slack:
        failures.append(
            f"overlap+staleness only cut exchange slack "
            f"{1 - trt_slack / ctl_slack:.0%} "
            f"({ctl_slack:.3f}s -> {trt_slack:.3f}s; bar is >= 40%)")
    else:
        print(f"chaos-smoke: exchange slack {ctl_slack:.3f}s -> "
              f"{trt_slack:.3f}s "
              f"({1 - trt_slack / ctl_slack:.0%} hidden)", flush=True)
    ev, recs, spans = trt
    errs = _schema.check_file(ev)
    if errs:
        failures.append(f"overlap events schema violations: {errs[:5]}")
    for needle in ("comm_overlap", "stale_join"):
        if not any(r.get("event") == needle for r in recs):
            failures.append(f"no typed {needle} event in the treatment "
                            f"stream")
    with open(os.path.join(outdir, "overlap-straggler.prom"), "w") as f:
        f.write(trace_report.metrics_text(spans))
    return failures


def ingest_cache_smoke(outdir: str, workdir: str) -> list:
    """The ISSUE-15 chaos-step variant: a supervised REAL-CLI training
    run with `--ingestCache` loses its worker to a deterministic SIGKILL
    mid-run; the relaunched generation must RE-INGEST ENTIRELY FROM THE
    SLAB CACHE — its typed ``ingest`` event reports cache=hit with zero
    bytes read (the shard artifacts are geometry-free, so a restart or
    shrink re-pays nothing) — and the run still completes its full round
    budget.  The worker event stream (incl. the typed ``ingest_cache``
    events) schema-validates and lands in the artifact dir."""
    from _faults import Fault, FaultPlan, checkpoint_at_least, sigkill

    failures = []
    ck = os.path.join(workdir, "ck_cache")
    cache_dir = os.path.join(workdir, "icache")
    ev = os.path.join(outdir, "cache-events.jsonl")
    train = os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "data",
        "small_train.dat")
    argv = [
        f"--trainFile={train}", "--numFeatures=9947", "--numSplits=4",
        "--numRounds=40", "--debugIter=10", "--localIterFrac=0.05",
        "--lambda=0.001", "--justCoCoA=true", f"--chkptDir={ck}",
        "--chkptIter=10", "--quiet", f"--ingestCache={cache_dir}",
        f"--events={ev}",
    ]
    plan = FaultPlan(
        Fault(generation=0, actions=(sigkill(0),),
              trigger=checkpoint_at_least(ck, "CoCoA+", 10),
              name="kill-worker"),
    )
    print("chaos-smoke: supervised CLI run with --ingestCache, SIGKILL "
          "mid-run, warm re-ingest", flush=True)
    rc = elastic.supervise(argv, 1, max_restarts=3, poll_s=0.05,
                           backoff_base_s=0.2,
                           on_generation=plan.on_generation)
    plan.join()
    if rc != 0:
        failures.append(f"cache-smoke supervised run exited {rc}")
    if plan.errors:
        failures.append(f"cache-smoke fault plan errors: {plan.errors}")
    if plan.fired != ["kill-worker"]:
        failures.append(f"cache-smoke fault never fired: {plan.fired}")
    path = ckpt_lib.latest(ck, "CoCoA+")
    if path is None:
        failures.append("cache-smoke: no final checkpoint")
    else:
        meta, _, _ = ckpt_lib.load(path)
        if meta["round"] != 40:
            failures.append(f"cache-smoke stopped at round "
                            f"{meta['round']}")
    errs = tele_schema.check_file(ev)
    if errs:
        failures.append(f"cache-smoke events schema violations: "
                        f"{errs[:5]}")
    recs = [json.loads(ln) for ln in open(ev)]
    ingests = [r for r in recs if r["event"] == "ingest"]
    if len(ingests) < 2:
        failures.append(f"cache-smoke: expected one ingest event per "
                        f"generation, got {len(ingests)}")
    else:
        if ingests[0]["cache"] != "miss":
            failures.append(f"cache-smoke: first generation should miss "
                            f"({ingests[0]['cache']})")
        last = ingests[-1]
        if last["cache"] != "hit" or last["bytes_read"] != 0:
            failures.append(
                f"cache-smoke: restarted generation re-parsed — "
                f"cache={last['cache']}, bytes_read="
                f"{last['bytes_read']} (the zero-reparse contract)")
        else:
            print(f"chaos-smoke: restart re-ingested warm (cache=hit, "
                  f"0 bytes re-parsed, cold paid "
                  f"{ingests[0]['bytes_read']} bytes)", flush=True)
    if not any(r["event"] == "ingest_cache" for r in recs):
        failures.append("cache-smoke: no typed ingest_cache event")
    return failures


if __name__ == "__main__":
    sys.exit(main())
