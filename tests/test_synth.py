"""Synthetic benchmark-dataset generators (cocoa_tpu/data/synth.py).

The generators exist to produce the baseline numbers the reference never
published (SURVEY.md #6, BASELINE.md): epsilon-like dense and rcv1-like
sparse stand-ins.  Validated here: statistical shape (unit rows, density,
label balance), determinism, equivalence of the device-side sharded
generator with the host->shard_dataset path's layout contract, LIBSVM
round-trips through both parsers, and that the planted problem is actually
solvable (the duality gap closes under CoCoA+).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from cocoa_tpu.config import DebugParams, Params
from cocoa_tpu.data import (
    load_libsvm,
    shard_dataset,
    synth_dense,
    synth_dense_sharded,
    synth_sparse,
    write_libsvm,
)
from cocoa_tpu.parallel import make_mesh


def test_synth_dense_stats():
    data = synth_dense(128, 40, seed=3)
    X = data.to_dense()
    np.testing.assert_allclose(np.linalg.norm(X, axis=1), 1.0, rtol=1e-12)
    assert set(np.unique(data.labels)) == {-1.0, 1.0}
    # planted separator -> roughly balanced labels
    assert 0.25 < np.mean(data.labels > 0) < 0.75
    # deterministic in the seed
    data2 = synth_dense(128, 40, seed=3)
    np.testing.assert_array_equal(data.values, data2.values)
    np.testing.assert_array_equal(data.labels, data2.labels)
    assert not np.array_equal(data.labels, synth_dense(128, 40, seed=4).labels)


def test_synth_sparse_stats():
    n, d, nnz_mean = 200, 500, 30
    data = synth_sparse(n, d, nnz_mean=nnz_mean, seed=1)
    row_nnz = np.diff(data.indptr)
    assert row_nnz.min() >= 1
    # Poisson(30) minus dedup loss keeps the mean in a wide band
    assert 15 <= row_nnz.mean() <= 35
    # rows are unit-normalized
    for i in range(0, n, 17):
        _, vals = data.row(i)
        np.testing.assert_allclose(np.linalg.norm(vals), 1.0, rtol=1e-12)
    # columns are Zipf-hot: low ids must be much more popular than the tail
    lo = np.sum(data.indices < d // 10)
    assert lo > 0.3 * data.indices.size
    # rows have no duplicate column ids (layout contract)
    for i in range(0, n, 13):
        idx, _ = data.row(i)
        assert np.unique(idx).size == idx.size
    assert 0.25 < np.mean(data.labels > 0) < 0.75


def test_write_libsvm_roundtrip(tmp_path):
    data = synth_sparse(60, 200, nnz_mean=12, seed=5)
    path = str(tmp_path / "synth.dat")
    write_libsvm(data, path, precision=17)
    for prefer_native in (False, True):
        back = load_libsvm(path, data.num_features,
                           prefer_native=prefer_native)
        np.testing.assert_array_equal(back.labels, data.labels)
        np.testing.assert_array_equal(back.indptr, data.indptr)
        np.testing.assert_array_equal(back.indices, data.indices)
        np.testing.assert_allclose(back.values, data.values, rtol=1e-15)


@pytest.mark.parametrize("mesh_k", [None, 4])
def test_synth_dense_sharded_layout(mesh_k):
    n, d, k = 100, 32, 4
    mesh = make_mesh(mesh_k) if mesh_k else None
    ds = synth_dense_sharded(n, d, k, seed=2, dtype=jnp.float64, mesh=mesh)
    assert ds.layout == "dense"
    assert ds.n == n and ds.num_features == d and ds.k == k
    assert ds.n_shard % 16 == 0
    counts = np.asarray(ds.counts)
    np.testing.assert_array_equal(counts, [25, 25, 25, 25])
    X = np.asarray(ds.X)
    mask = np.asarray(ds.mask)
    labels = np.asarray(ds.labels)
    sq = np.asarray(ds.sq_norms)
    for s in range(k):
        c = counts[s]
        # real rows: unit norm, +-1 labels, mask 1, sq_norms match X
        np.testing.assert_allclose(
            np.linalg.norm(X[s, :c], axis=1), 1.0, rtol=1e-6)
        assert set(np.unique(labels[s, :c])) <= {-1.0, 1.0}
        np.testing.assert_array_equal(mask[s, :c], 1.0)
        np.testing.assert_allclose(
            sq[s], np.sum(X[s] * X[s], axis=-1), rtol=1e-6)
        # padded rows zeroed
        np.testing.assert_array_equal(X[s, c:], 0.0)
        np.testing.assert_array_equal(labels[s, c:], 0.0)
        np.testing.assert_array_equal(mask[s, c:], 0.0)
    if mesh is not None:
        assert len(ds.X.sharding.device_set) == mesh_k


def test_synth_dense_sharded_mesh_invariant():
    """Same (n, d, k, seed) -> same data with and without a mesh."""
    n, d, k = 64, 16, 4
    a = synth_dense_sharded(n, d, k, seed=9, dtype=jnp.float32)
    b = synth_dense_sharded(n, d, k, seed=9, dtype=jnp.float32,
                            mesh=make_mesh(4))
    np.testing.assert_array_equal(np.asarray(a.X), np.asarray(b.X))
    np.testing.assert_array_equal(np.asarray(a.labels), np.asarray(b.labels))


def test_synth_dense_sharded_fp_mesh():
    """fp mesh: columns split over the feature axis, d padded to a multiple."""
    mesh = make_mesh(4, fp=2)
    ds = synth_dense_sharded(50, 30, 4, seed=1, dtype=jnp.float32, mesh=mesh)
    assert ds.num_features == 32  # lcm(fp=2, sublane=8) multiple
    shapes = {s.data.shape for s in ds.X.addressable_shards}
    assert shapes == {(1, ds.n_shard, 16)}


def test_synth_problem_converges():
    """The planted problem is solvable: CoCoA+ closes the duality gap."""
    from cocoa_tpu.solvers import run_cocoa

    n, d, k = 256, 32, 4
    ds = synth_dense_sharded(n, d, k, seed=0, flip=0.02, dtype=jnp.float64)
    params = Params(n=n, num_rounds=300, local_iters=64, lam=1e-3)
    debug = DebugParams(debug_iter=25, seed=0)
    _, _, traj = run_cocoa(ds, params, debug, plus=True, quiet=True,
                           gap_target=1e-3)
    assert traj.records[-1].gap is not None
    assert traj.records[-1].gap <= 1e-3


def test_synth_sparse_solvable_via_shard_dataset():
    """synth_sparse -> shard_dataset(sparse layout) -> CoCoA converges."""
    from cocoa_tpu.solvers import run_cocoa

    data = synth_sparse(240, 300, nnz_mean=20, seed=3)
    ds = shard_dataset(data, k=4, layout="sparse", dtype=jnp.float64)
    # 400 rounds: the round-4 tf-idf value distribution (heavier value
    # skew) conditions this tiny planted problem a bit worse than the
    # round-3 iid values — the property under test is convergence
    params = Params(n=data.n, num_rounds=400, local_iters=60, lam=1e-3)
    _, _, traj = run_cocoa(ds, params, DebugParams(debug_iter=25, seed=0),
                           plus=True, quiet=True, gap_target=5e-3)
    assert traj.records[-1].gap <= 5e-3
