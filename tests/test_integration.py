"""Demo-equivalent integration tests on the bundled reference data
(run-demo-local.sh config: K=4, H=50, λ=1e-3), abbreviated to keep CI fast.
The full 100-round run reaches gap ≈ 4.7e-3 and test error 2.5%."""

import jax.numpy as jnp
import numpy as np
import pytest

from conftest import SMALL_TRAIN, SMALL_TEST  # noqa: E402
from cocoa_tpu.config import DebugParams, Params
from cocoa_tpu.data import shard_dataset
from cocoa_tpu.parallel import make_mesh
from cocoa_tpu.solvers import run_cocoa


@pytest.fixture(scope="module")
def demo(small_train, small_test):
    mesh = make_mesh(4)
    ds = shard_dataset(small_train, k=4, layout="sparse", dtype=jnp.float64, mesh=mesh)
    tds = shard_dataset(small_test, k=4, layout="sparse", dtype=jnp.float64, mesh=mesh)
    params = Params(n=2000, num_rounds=30, local_iters=50, lam=0.001,
                    beta=1.0, gamma=1.0)
    return mesh, ds, tds, params


@pytest.mark.parametrize("plus", [True, False])
def test_demo_converges(demo, plus):
    mesh, ds, tds, params = demo
    debug = DebugParams(debug_iter=10, seed=0)
    w, alpha, traj = run_cocoa(
        ds, params, debug, plus=plus, mesh=mesh, test_ds=tds, quiet=True
    )
    gaps = [r.gap for r in traj.records]
    errs = [r.test_error for r in traj.records]
    # gap decreasing across checkpoints, non-negative, below .1 by round 30
    assert all(g >= 0 for g in gaps)
    assert gaps[-1] < gaps[0]
    assert gaps[-1] < 0.1
    # linear SVM on this data sits at ~2.5% test error
    assert errs[-1] < 0.06
    # alpha in box, w finite
    assert np.all(np.isfinite(np.asarray(w)))
    a = np.asarray(alpha)
    assert a.min() >= -1e-12 and a.max() <= 1 + 1e-12


def test_cli_end_to_end(capsys):
    from cocoa_tpu import cli

    rc = cli.main([
        f"--trainFile={SMALL_TRAIN}",
        f"--testFile={SMALL_TEST}",
        "--numFeatures=9947",
        "--numSplits=4",
        "--numRounds=10",
        "--localIterFrac=0.1",
        "--lambda=.001",
        "--debugIter=5",
        "--justCoCoA=true",
        "--dtype=float64",
        "--master=local[4]",  # accepted-and-ignored reference flag
    ])
    out = capsys.readouterr().out
    assert rc == 0
    assert "Running CoCoA+ on 2000 data examples" in out
    assert "Running CoCoA on 2000 data examples" in out
    assert "primal-dual gap:" in out
    assert "CoCoA+ has finished running. Summary Stats:" in out
    assert "Duality Gap:" in out
    assert "Test Error:" in out


def test_cli_rejects_unknown_flag():
    from cocoa_tpu import cli

    with pytest.raises(SystemExit, match="Invalid argument: --bogus"):
        cli.parse_args(["--bogus=1"])


def test_cli_requires_trainfile(capsys):
    from cocoa_tpu import cli

    assert cli.main(["--numFeatures=5"]) == 2
    assert "trainFile is required" in capsys.readouterr().err
