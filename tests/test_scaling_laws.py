"""Scaling laws tested OFF their fixed points (VERDICT r1 item 4).

Every round-1 oracle-trajectory test ran at β=1, γ=1 — the exact values at
which a transposed γ/σ′ or a misapplied ``scaling`` in
``solvers/cocoa.py:_alg_config`` could cancel out and pass.  The reference
explicitly parameterizes both (hingeDriver.scala:35-36; γ=1/K is the
documented averaging variant), so here every algorithm's trajectory is
matched against the literal oracle at β ∈ {0.5, 2} and γ ∈ {1/K, 0.5},
including one fast-math and one Pallas(interpret) configuration.
"""

import jax.numpy as jnp
import numpy as np
import pytest

import oracle
from cocoa_tpu.config import DebugParams, Params
from cocoa_tpu.data.sharding import shard_dataset, split_sizes
from cocoa_tpu.solvers import run_cocoa, run_dist_gd, run_minibatch_cd, run_sgd
from cocoa_tpu.utils.prng import sample_indices

K = 4
H = 20


def _params(tiny_data, **kw):
    defaults = dict(n=tiny_data.n, num_rounds=5, local_iters=H, lam=0.01,
                    beta=1.0, gamma=1.0)
    defaults.update(kw)
    return Params(**defaults)


_DBG = DebugParams(debug_iter=-1, seed=0)


def _shards(tiny_data):
    X = tiny_data.to_dense()
    y = tiny_data.labels
    sizes = split_sizes(tiny_data.n, K)
    offs = np.concatenate([[0], np.cumsum(sizes)])
    return [(X[offs[i]:offs[i + 1]], y[offs[i]:offs[i + 1]])
            for i in range(K)]


def _sample_fn(seed, t, n_local):
    return sample_indices(seed, range(t, t + 1), H, n_local)[0]


@pytest.mark.parametrize("gamma", [1.0 / K, 0.5])
def test_cocoa_plus_gamma_off_fixed_point(tiny_data, gamma):
    """CoCoA+ at γ≠1: scaling=γ and σ′=K·γ are distinct numbers here, so a
    swap or misapplication in _alg_config/per_shard cannot cancel."""
    ds = shard_dataset(tiny_data, k=K, layout="dense", dtype=jnp.float64)
    p = _params(tiny_data, gamma=gamma)
    w, alpha, _ = run_cocoa(ds, p, _DBG, plus=True, quiet=True)
    w_o, alphas_o = oracle.cocoa_outer(
        _shards(tiny_data), np.zeros(tiny_data.num_features),
        p.lam, p.n, p.num_rounds, H, p.beta, gamma, 0, True, _sample_fn,
    )
    np.testing.assert_allclose(np.asarray(w), w_o, atol=1e-12)
    for s in range(K):
        np.testing.assert_allclose(
            np.asarray(alpha[s, : len(alphas_o[s])]), alphas_o[s], atol=1e-12
        )


@pytest.mark.parametrize("beta", [0.5, 2.0])
def test_cocoa_beta_off_fixed_point(tiny_data, beta):
    """CoCoA (averaging) at β≠1: scaling = β/K (CoCoA.scala:37)."""
    ds = shard_dataset(tiny_data, k=K, layout="dense", dtype=jnp.float64)
    p = _params(tiny_data, beta=beta)
    w, alpha, _ = run_cocoa(ds, p, _DBG, plus=False, quiet=True)
    w_o, alphas_o = oracle.cocoa_outer(
        _shards(tiny_data), np.zeros(tiny_data.num_features),
        p.lam, p.n, p.num_rounds, H, beta, p.gamma, 0, False, _sample_fn,
    )
    np.testing.assert_allclose(np.asarray(w), w_o, atol=1e-12)
    for s in range(K):
        np.testing.assert_allclose(
            np.asarray(alpha[s, : len(alphas_o[s])]), alphas_o[s], atol=1e-12
        )


@pytest.mark.parametrize("beta", [0.5, 2.0])
def test_minibatch_cd_beta_off_fixed_point(tiny_data, beta):
    """MbCD at β≠1: scaling = β/(K·H) (MinibatchCD.scala:32)."""
    ds = shard_dataset(tiny_data, k=K, layout="dense", dtype=jnp.float64)
    p = _params(tiny_data, beta=beta, num_rounds=4)
    w, alpha, _ = run_minibatch_cd(ds, p, _DBG, quiet=True)

    scaling = beta / (K * H)
    w_o = np.zeros(tiny_data.num_features)
    shards = _shards(tiny_data)
    alphas_o = [np.zeros(Xk.shape[0]) for Xk, _ in shards]
    for t in range(1, p.num_rounds + 1):
        dw_sum = np.zeros_like(w_o)
        for s, (Xk, yk) in enumerate(shards):
            idxs = _sample_fn(0, t, Xk.shape[0])
            dw, a_new = oracle.minibatch_cd_partition(
                Xk, yk, w_o, alphas_o[s], idxs, p.lam, p.n, scaling
            )
            alphas_o[s] = a_new
            dw_sum += dw
        w_o = w_o + dw_sum * scaling
    np.testing.assert_allclose(np.asarray(w), w_o, atol=1e-12)
    for s in range(K):
        np.testing.assert_allclose(
            np.asarray(alpha[s, : len(alphas_o[s])]), alphas_o[s], atol=1e-12
        )


@pytest.mark.parametrize("local", [True, False])
@pytest.mark.parametrize("beta", [0.5, 2.0])
def test_sgd_beta_off_fixed_point(tiny_data, local, beta):
    """SGD at β≠1: scaling = β/K (local) | β/(K·H) (mini-batch)
    (SGD.scala:34-39)."""
    ds = shard_dataset(tiny_data, k=K, layout="dense", dtype=jnp.float64)
    p = _params(tiny_data, beta=beta, num_rounds=4)
    w, _ = run_sgd(ds, p, _DBG, local=local, quiet=True)

    scaling = beta / K if local else beta / (K * H)
    w_o = np.zeros(tiny_data.num_features)
    shards = _shards(tiny_data)
    for t in range(1, p.num_rounds + 1):
        eta = 1.0 / (p.lam * t)
        if not local:
            w_o = w_o * (1.0 - eta * p.lam)
        t_global = (t - 1) * H * K
        dw_sum = np.zeros_like(w_o)
        for Xk, yk in shards:
            idxs = _sample_fn(0, t, Xk.shape[0])
            dw_sum += oracle.sgd_partition(
                Xk, yk, w_o, idxs, p.lam, t_global, local
            )
        w_o = w_o + dw_sum * (scaling if local else eta * scaling)
    np.testing.assert_allclose(np.asarray(w), w_o, atol=1e-12)


@pytest.mark.parametrize("beta", [0.5, 2.0])
def test_dist_gd_beta_off_fixed_point(tiny_data, beta):
    """DistGD at β≠1: η = 1/(β·t) (DistGD.scala:35)."""
    ds = shard_dataset(tiny_data, k=K, layout="dense", dtype=jnp.float64)
    p = _params(tiny_data, beta=beta, num_rounds=4)
    w, _ = run_dist_gd(ds, p, _DBG, quiet=True)

    w_o = np.zeros(tiny_data.num_features)
    shards = _shards(tiny_data)
    for t in range(1, p.num_rounds + 1):
        eta = 1.0 / (beta * t)
        dw_sum = np.zeros_like(w_o)
        for Xk, yk in shards:
            dw_sum += oracle.dist_gd_partition(Xk, yk, w_o, p.lam)
        w_o = w_o + dw_sum * (eta / np.linalg.norm(dw_sum))
    np.testing.assert_allclose(np.asarray(w), w_o, atol=1e-12)


def test_fast_math_gamma_off_fixed_point(tiny_data):
    """Fast math must apply the same (scaling, σ′) pair: loose trajectory
    agreement with the oracle at γ=0.5 (fp rounds differ — the margins
    decomposition reorders the arithmetic, ops/local_sdca.mode_factors)."""
    gamma = 0.5
    ds = shard_dataset(tiny_data, k=K, layout="dense", dtype=jnp.float64)
    p = _params(tiny_data, gamma=gamma)
    w, _, _ = run_cocoa(ds, p, _DBG, plus=True, quiet=True, math="fast")
    w_o, _ = oracle.cocoa_outer(
        _shards(tiny_data), np.zeros(tiny_data.num_features),
        p.lam, p.n, p.num_rounds, H, p.beta, gamma, 0, True, _sample_fn,
    )
    np.testing.assert_allclose(np.asarray(w), w_o, rtol=1e-6, atol=1e-8)


@pytest.mark.slow
@pytest.mark.parametrize("layout", ["dense", "sparse"])
def test_pallas_gamma_off_fixed_point(tiny_data, layout):
    """The Pallas kernels (interpret mode on CPU) must agree with the
    oracle-anchored fast path at γ=0.5 to near-machine precision."""
    gamma = 0.5
    ds = shard_dataset(tiny_data, k=K, layout=layout, dtype=jnp.float64)
    p = _params(tiny_data, gamma=gamma)
    w_f, a_f, _ = run_cocoa(ds, p, _DBG, plus=True, quiet=True,
                            math="fast", pallas=False, scan_chunk=5)
    w_p, a_p, _ = run_cocoa(ds, p, _DBG, plus=True, quiet=True,
                            math="fast", pallas=True, scan_chunk=5)
    np.testing.assert_allclose(np.asarray(w_p), np.asarray(w_f),
                               rtol=1e-9, atol=1e-11)
    np.testing.assert_allclose(np.asarray(a_p), np.asarray(a_f),
                               rtol=1e-9, atol=1e-11)
