"""The serving subsystem (cocoa_tpu/serving/, docs/DESIGN.md §17):
compiled bucket scoring vs a numpy reference, one-compile-per-bucket
across hot-swaps, atomic swap semantics under traffic, the adaptive
micro-batcher, the checkpoint-validation cache, the TCP protocol, and
the serve telemetry — plus the chaos pin: serving keeps answering
through a SIGKILL-triggered elastic shrink of the background trainer.
"""

import json
import os
import socket
import threading
import time

import numpy as np
import pytest

from cocoa_tpu import checkpoint as ckpt_lib
from cocoa_tpu import serving
from cocoa_tpu.analysis import sanitize
from cocoa_tpu.serving.watcher import emit_model_swap
from cocoa_tpu.telemetry import events as tele
from cocoa_tpu.telemetry import schema as tele_schema

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TESTS = os.path.dirname(os.path.abspath(__file__))

D = 24


@pytest.fixture
def bus(tmp_path):
    """An armed bus writing to a per-test JSONL, reset afterwards."""
    b = tele.get_bus()
    b.reset()
    path = tmp_path / "events.jsonl"
    b.configure(jsonl_path=str(path))
    yield path
    b.reset()


def _read_events(path):
    with open(path) as f:
        return [json.loads(ln) for ln in f if ln.strip()]


def _save_model(ck, w, round_t, gap=None, algorithm="CoCoA+"):
    return ckpt_lib.save(str(ck), algorithm, round_t,
                         np.asarray(w, np.float32), None, gap=gap)


def _serving_stack(ck, buckets=(4, 16), max_nnz=8, sla_s=0.01,
                   algorithm="CoCoA+"):
    w, info = serving.load_model(ckpt_lib.latest(str(ck), algorithm))
    slots = serving.ModelSlots(w, info, dtype=np.float32)
    scorer = serving.BatchScorer(D, dtype=np.float32, buckets=buckets,
                                 max_nnz=max_nnz)
    scorer.warmup(slots.current()[0])
    batcher = serving.MicroBatcher(scorer, slots, sla_s=sla_s,
                                   algorithm=algorithm)
    return slots, scorer, batcher


def _rand_queries(rng, n, max_nnz=8):
    out = []
    for _ in range(n):
        nnz = int(rng.integers(1, max_nnz + 1))
        idx = rng.choice(D, size=nnz, replace=False).astype(np.int32)
        val = rng.standard_normal(nnz)
        out.append((np.sort(idx), val[np.argsort(idx)]))
    return out


def _ref_margin(w32, idx, val):
    # f64 reference accumulation of the f32 addends: identifies the
    # model generation unambiguously; bitwise pins are reserved for
    # same-compiled-path comparisons (swap vs cold restart), where the
    # executable and inputs are identical by construction
    val32 = np.asarray(val, np.float32)   # the cast assembly performs
    return (np.asarray(w32, np.float64)[np.asarray(idx)]
            * val32.astype(np.float64)).sum()


def _assert_margin(m, w32, qi, qv):
    np.testing.assert_allclose(np.float64(m),
                               _ref_margin(w32, qi, qv),
                               rtol=1e-5, atol=1e-5)


# --- query grammar -----------------------------------------------------------


def test_parse_query_grammar_and_rejections():
    idx, val = serving.parse_query("1:0.5 3:-2 24:1e-3", D, 8)
    assert idx.tolist() == [0, 2, 23]
    np.testing.assert_allclose(val, [0.5, -2.0, 1e-3])
    with pytest.raises(serving.QueryError, match=r"feature id 25.*"
                                                 r"num_features=24"):
        serving.parse_query("25:1.0", D, 8)
    with pytest.raises(serving.QueryError, match="malformed"):
        serving.parse_query("3:", D, 8)
    with pytest.raises(serving.QueryError, match=r"3 nonzeros.*"
                                                 r"max_nnz=2"):
        serving.parse_query("1:1 2:1 3:1", D, 2)
    with pytest.raises(serving.QueryError, match="empty"):
        serving.parse_query("   ", D, 8)


# --- the compiled scoring path ----------------------------------------------


def test_scorer_matches_reference_across_buckets(tmp_path):
    rng = np.random.default_rng(0)
    w32 = rng.standard_normal(D).astype(np.float32)
    _save_model(tmp_path, w32, 10)
    slots, scorer, _ = _serving_stack(tmp_path)
    for n in (1, 3, 4, 9, 16):
        queries = _rand_queries(rng, n)
        bucket = serving.pick_bucket(n, scorer.buckets)
        idx, val, hot = scorer.assemble(queries, bucket)
        out = np.asarray(scorer.score(slots.current()[0], idx, val, hot))
        assert out.shape == (bucket,)
        for r, (qi, qv) in enumerate(queries):
            _assert_margin(out[r], w32, qi, qv)
        # padded slots contribute exactly zero
        np.testing.assert_array_equal(out[n:], 0.0)


def test_scorer_hybrid_rides_panel_plus_residual(tmp_path):
    """A hot/cold split scorer answers the same margins as the plain
    gather path (fp reassociated — the §3b-vi contract), through the
    same shard_margins dispatch the evaluator uses."""
    rng = np.random.default_rng(1)
    w32 = rng.standard_normal(D).astype(np.float32)
    hot_ids = np.array([2, 5, 7, 11], np.int64)
    plain = serving.BatchScorer(D, dtype=np.float32, buckets=(8,),
                                max_nnz=8)
    hybrid = serving.BatchScorer(D, dtype=np.float32, buckets=(8,),
                                 max_nnz=8, hot_ids=hot_ids)
    assert hybrid.n_hot == 4
    queries = _rand_queries(rng, 6)
    import jax

    w_dev = jax.device_put(w32)
    ip, vp, hp = plain.assemble(queries, 8)
    assert hp is None
    ih, vh, hh = hybrid.assemble(queries, 8)
    assert hh.shape == (8, 4)
    out_p = np.asarray(plain.score(w_dev, ip, vp, hp))
    out_h = np.asarray(hybrid.score(w_dev, ih, vh, hh))
    np.testing.assert_allclose(out_p, out_h, atol=1e-5)
    # the residual really lost the hot entries: no hot column id appears
    # in a residual slot with a nonzero value
    assert not np.any(np.isin(ih, hot_ids) & (vh != 0))


def test_one_compile_per_bucket_across_hot_swaps(tmp_path):
    """The acceptance pin: N hot-swaps, zero new compiles — and the
    post-swap margins are bit-identical to a cold restart on the new
    checkpoint."""
    rng = np.random.default_rng(2)
    w1 = rng.standard_normal(D).astype(np.float32)
    _save_model(tmp_path, w1, 10, gap=1e-3)
    queries = _rand_queries(rng, 5)
    with sanitize.watch_compiles() as compiles:
        slots, scorer, batcher = _serving_stack(tmp_path)
        n_warm = len([c for c in compiles if "serve_margins" in c.name])
        assert n_warm == len(scorer.buckets) == 2
        watcher = serving.SwapWatcher(slots, str(tmp_path), "CoCoA+")
        w_new = w1
        for gen in range(3):   # three swapped generations
            w_new = (w_new * 0.7 + gen).astype(np.float32)
            _save_model(tmp_path, w_new, 20 + 10 * gen, gap=1e-4)
            assert watcher.poll_once()
            for n in (1, 7):   # both buckets, post-swap
                bucket = serving.pick_bucket(n, scorer.buckets)
                idx, val, hot = scorer.assemble(queries[:n], bucket)
                np.asarray(scorer.score(slots.current()[0], idx, val,
                                        hot))
        total = len([c for c in compiles if "serve_margins" in c.name])
    assert total == n_warm, (
        f"hot-swaps recompiled: {total} compiles for "
        f"{len(scorer.buckets)} buckets")
    assert watcher.swaps_total == 3
    # bit-identity vs a cold restart on the final checkpoint
    cold = serving.BatchScorer(D, dtype=np.float32,
                               buckets=scorer.buckets, max_nnz=8)
    w_cold, _ = serving.load_model(ckpt_lib.latest(str(tmp_path),
                                                   "CoCoA+"))
    import jax

    w_cold_dev = jax.device_put(np.asarray(w_cold, np.float32))
    idx, val, hot = scorer.assemble(queries, 8)
    hot_live = np.asarray(scorer.score(slots.current()[0], idx, val,
                                       hot))
    cold_out = np.asarray(cold.score(w_cold_dev, idx, val, hot))
    np.testing.assert_array_equal(hot_live, cold_out)
    batcher.stop()


def test_swap_rejects_width_change_with_numbers(tmp_path, capsys):
    w = np.zeros(D, np.float32)
    _save_model(tmp_path, w, 10)
    slots, scorer, batcher = _serving_stack(tmp_path)
    with pytest.raises(serving.QueryError, match=r"\(12,\).*\(24,\)"):
        slots.swap(np.zeros(12, np.float32),
                   slots.info._replace(seq=1))
    # through the watcher: rejected loudly, old model keeps serving,
    # and the bad generation is not retried every poll
    ckpt_lib.save(str(tmp_path), "CoCoA+", 20,
                  np.zeros(12, np.float32), None)
    watcher = serving.SwapWatcher(slots, str(tmp_path), "CoCoA+")
    assert not watcher.poll_once()
    assert watcher.rejected_total == 1 and watcher.swaps_total == 0
    assert slots.info.round == 10
    assert not watcher.poll_once()       # cached rejection: no relooping
    assert watcher.rejected_total == 1
    err = capsys.readouterr().err
    assert "(12,)" in err and "(24,)" in err
    batcher.stop()


# --- the micro-batcher -------------------------------------------------------


def test_batcher_pads_to_bucket_and_reports_fill(tmp_path, bus):
    w = np.arange(D, dtype=np.float32)
    _save_model(tmp_path, w, 5, gap=2e-3)
    slots, scorer, batcher = _serving_stack(tmp_path, sla_s=0.05)
    queries = _rand_queries(np.random.default_rng(3), 3)
    pendings = [batcher.submit(qi, qv) for qi, qv in queries]
    margins = [p.result(timeout=10.0) for p in pendings]
    for (qi, qv), m in zip(queries, margins):
        _assert_margin(m, w, qi, qv)
    assert all(p.model_round == 5 for p in pendings)
    batcher.stop()
    reqs = [r for r in _read_events(bus) if r["event"] == "serve_request"]
    assert reqs, "no serve_request events"
    assert sum(r["n"] for r in reqs) == 3
    for r in reqs:
        assert r["bucket"] in scorer.buckets
        assert 0 < r["fill_ratio"] <= 1.0
        assert r["queue_s"] >= 0 and r["device_s"] > 0
        assert r["latency_max_s"] >= r["latency_mean_s"] > 0
        assert r["model_round"] == 5
    assert tele_schema.check_file(str(bus)) == []


def test_batcher_one_intended_fetch_per_batch(tmp_path, bus):
    """The zero-unintended-transfers contract, observable: every scored
    batch crosses device→host exactly once, through intended_fetch."""
    w = np.ones(D, np.float32)
    _save_model(tmp_path, w, 5)
    slots, scorer, batcher = _serving_stack(tmp_path)
    for _ in range(3):
        batcher.score_sync(np.array([0], np.int32),
                           np.array([1.0]), timeout=10.0)
    batcher.stop()
    recs = _read_events(bus)
    fetches = [r for r in recs if r["event"] == "host_transfer"
               and r["label"] == "serve_fetch"]
    batches = [r for r in recs if r["event"] == "serve_request"]
    assert len(fetches) == len(batches) >= 1


def test_batcher_spans_attribute_queue_vs_device(tmp_path, bus):
    """--trace on a serving run: every batch leaves a serve_admit span
    (queueing) and a serve_score span (device dispatch+fetch) — what
    trace_report attributes the wall-clock with."""
    from cocoa_tpu.telemetry import tracing

    tracing.configure(enabled=True, worker=0)
    try:
        w = np.ones(D, np.float32)
        _save_model(tmp_path, w, 5)
        slots, scorer, batcher = _serving_stack(tmp_path)
        batcher.score_sync(np.array([0], np.int32), np.array([1.0]),
                           timeout=10.0)
        batcher.stop()
    finally:
        tracing.reset()
    spans = [r for r in _read_events(bus) if r["event"] == "span"]
    phases = {s["phase"] for s in spans}
    assert {"serve_admit", "serve_score"} <= phases, phases
    score = [s for s in spans if s["phase"] == "serve_score"]
    assert score[0]["dur_s"] > 0 and score[0]["bucket"] in scorer.buckets
    assert tele_schema.check_file(str(bus)) == []


# --- the swap watcher + freshness -------------------------------------------


def test_watcher_swaps_and_exports_gap_age(tmp_path, bus):
    w = np.zeros(D, np.float32)
    _save_model(tmp_path, w, 10, gap=1e-2)
    slots, scorer, batcher = _serving_stack(tmp_path)
    emit_model_swap("CoCoA+", slots.info)       # the initial publish
    age0 = slots.gap_age_s()
    assert age0 >= 0.0
    _save_model(tmp_path, w + 1, 20, gap=1e-3)
    watcher = serving.SwapWatcher(slots, str(tmp_path), "CoCoA+")
    assert watcher.poll_once()
    assert slots.info.round == 20 and slots.info.gap == 1e-3
    assert slots.gap_age_s() <= age0 + 1.0      # fresher certificate
    batcher.stop()
    swaps = [r for r in _read_events(bus) if r["event"] == "model_swap"]
    assert len(swaps) == 2
    assert swaps[-1]["round"] == 20
    assert swaps[-1]["gap"] == 1e-3
    assert swaps[-1]["gap_age_s"] >= 0
    assert swaps[-1]["swap_seq"] == 1
    assert tele_schema.check_file(str(bus)) == []


def test_checkpoint_validation_cache(tmp_path, monkeypatch):
    """Unchanged generations cost one stat; a rewritten-in-place file
    (same path, new mtime) re-validates — including a corrupt rewrite,
    which must still fall back."""
    calls = []
    real = ckpt_lib._validate

    def counting(path):
        calls.append(path)
        return real(path)

    monkeypatch.setattr(ckpt_lib, "_validate", counting)
    w = np.ones(8, np.float32)
    p10 = ckpt_lib.save(str(tmp_path), "CoCoA+", 10, w, None)
    assert ckpt_lib.latest(str(tmp_path), "CoCoA+") == p10
    first = len(calls)
    assert first == 1
    for _ in range(5):   # poll-rate reads: stat only
        assert ckpt_lib.latest(str(tmp_path), "CoCoA+") == p10
    assert len(calls) == first
    # rewritten in place (same path, same round, new content/mtime):
    # must NOT serve the stale pass
    ckpt_lib.save(str(tmp_path), "CoCoA+", 10, w * 2, None)
    assert ckpt_lib.latest(str(tmp_path), "CoCoA+") == p10
    assert len(calls) == first + 1
    # corrupt in-place rewrite of a NEWER generation: re-validated,
    # rejected, clean fallback to the cached-good r10
    p20 = ckpt_lib.save(str(tmp_path), "CoCoA+", 20, w, None)
    assert ckpt_lib.latest(str(tmp_path), "CoCoA+") == p20
    with open(p20, "wb") as f:
        f.write(b"torn")
    assert ckpt_lib.latest(str(tmp_path), "CoCoA+") == p10
    assert ckpt_lib.latest(str(tmp_path), "CoCoA+") == p10


# --- the TCP protocol --------------------------------------------------------


def test_server_protocol_batches_errors_shutdown(tmp_path):
    w = np.arange(D, dtype=np.float32)
    _save_model(tmp_path, w, 7)
    slots, scorer, batcher = _serving_stack(tmp_path)
    srv = serving.MarginServer(batcher, D, 8, port=0)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        s = socket.create_connection(srv.address, timeout=10)
        f = s.makefile("rwb")
        f.write(b"1:1.0;3:2.0;99:1.0\n")
        f.flush()
        resp = json.loads(f.readline())
        assert isinstance(resp, list) and len(resp) == 3
        _assert_margin(resp[0]["margin"], w, [0], [1.0])
        assert resp[0]["round"] == 7
        assert "feature id 99" in resp[2]["error"]   # per-query reject
        f.write(b"2:1.5\n")
        f.flush()
        single = json.loads(f.readline())
        assert isinstance(single, dict) and single["round"] == 7
        f.write(b"shutdown\n")
        f.flush()
        assert json.loads(f.readline())["ok"] == "shutting down"
        s.close()
        t.join(10)
        assert not t.is_alive()
    finally:
        srv.close()
        batcher.stop()


# --- serve metrics families --------------------------------------------------


def test_serve_metrics_families_rendered(tmp_path):
    from cocoa_tpu.telemetry.metrics import MetricsWriter

    path = str(tmp_path / "m.prom")
    wtr = MetricsWriter(path)
    base = {"seq": 1, "pid": 1, "ts": 1000.0}
    wtr({**base, "event": "serve_request", "n": 3, "bucket": 4,
         "fill_ratio": 0.75, "queue_s": 0.001, "device_s": 0.002,
         "latency_max_s": 0.004, "latency_mean_s": 0.003,
         "model_round": 10})
    wtr({**base, "event": "model_swap", "round": 10, "path": "x",
         "birth_ts": time.time() - 2.0, "gap": 1e-3,
         "gap_age_s": 2.0, "swap_seq": 1})
    text = open(path).read()
    for needle in ("cocoa_serve_qps", "cocoa_serve_requests_total 3",
                   "cocoa_serve_batch_fill_ratio 0.75",
                   "cocoa_serve_latency_seconds_count 1",
                   "cocoa_model_swaps_total 1",
                   "cocoa_model_gap_age_seconds"):
        assert needle in text, f"{needle} missing from:\n{text}"
    age = float([ln for ln in text.splitlines()
                 if ln.startswith("cocoa_model_gap_age_seconds")][0]
                .split()[1])
    assert 1.5 <= age <= 30.0   # render-time age, anchored on birth_ts
    # training-only runs must not render serve families
    clean = str(tmp_path / "clean.prom")
    MetricsWriter(clean)
    assert "cocoa_serve" not in open(clean).read()


def test_scorer_duplicate_ids_sum_on_both_paths():
    """A query may repeat a feature id; the gather path sums duplicates
    (each occupies its own slot), so the hot panel must ACCUMULATE them
    too — a --hotCols server and a plain one answer identically."""
    import jax

    w32 = np.linspace(-1, 1, D).astype(np.float32)
    w_dev = jax.device_put(w32)
    hot_ids = np.array([2, 5], np.int64)
    plain = serving.BatchScorer(D, dtype=np.float32, buckets=(4,),
                                max_nnz=8)
    hybrid = serving.BatchScorer(D, dtype=np.float32, buckets=(4,),
                                 max_nnz=8, hot_ids=hot_ids)
    # feature 3 (0-based id 2) is hot and appears twice
    qi, qv = serving.parse_query("3:1.0 3:2.0 7:1.0", D, 8)
    outs = []
    for scorer in (plain, hybrid):
        idx, val, hot = scorer.assemble([(qi, qv)], 4)
        outs.append(np.asarray(scorer.score(w_dev, idx, val, hot))[0])
    np.testing.assert_allclose(outs[0], outs[1], rtol=1e-6)
    _assert_margin(outs[1], w32, qi, qv)   # duplicates summed, not last


def test_metrics_heartbeat_keeps_gap_age_climbing(tmp_path):
    """The alert scenario: a dead trainer and an idle server emit no
    events — the heartbeat's unconditional rewrites must keep the
    render-time gap-age gauge climbing anyway."""
    from cocoa_tpu.telemetry.metrics import MetricsWriter

    path = str(tmp_path / "m.prom")
    wtr = MetricsWriter(path)
    wtr({"event": "model_swap", "seq": 1, "pid": 1, "ts": 1.0,
         "round": 10, "path": "x", "birth_ts": time.time() - 1.0,
         "gap": 1e-3, "gap_age_s": 1.0, "swap_seq": 0})

    def age():
        ln = [x for x in open(path).read().splitlines()
              if x.startswith("cocoa_model_gap_age_seconds")][0]
        return float(ln.split()[1])

    a0 = age()
    wtr.start_heartbeat(0.05)
    try:
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and age() <= a0:
            time.sleep(0.05)
        assert age() > a0, "gauge frozen with no events"
    finally:
        wtr.stop_heartbeat()
    # swap_seq 0 (the initial load) anchors the gauge but is NOT a swap
    assert "cocoa_model_swaps_total 0" in open(path).read()


def test_event_envelope_collision_guard(bus):
    with pytest.raises(ValueError, match="envelope"):
        tele.get_bus().emit("model_swap", algorithm="x", round=1,
                            path="p", birth_ts=0.0, gap=None,
                            gap_age_s=0.0, seq=3)


# --- swap under sustained traffic (the acceptance pin) -----------------------


@pytest.mark.slow
def test_swap_under_sustained_traffic_drops_nothing(tmp_path, bus):
    """Hot-swaps land while client threads hammer the batcher: zero
    dropped/failed requests, every answer is bit-exact under the model
    generation that answered it, and the post-drain margins equal a
    cold restart on the final checkpoint."""
    rng = np.random.default_rng(4)
    gens = {10: rng.standard_normal(D).astype(np.float32)}
    _save_model(tmp_path, gens[10], 10, gap=1e-3)
    slots, scorer, batcher = _serving_stack(tmp_path, sla_s=0.02)
    watcher = serving.SwapWatcher(slots, str(tmp_path), "CoCoA+",
                                  poll_s=0.01).start()
    stop = threading.Event()
    failures, answers = [], []
    lock = threading.Lock()

    def client(seed):
        crng = np.random.default_rng(seed)
        while not stop.is_set():
            (qi, qv), = _rand_queries(crng, 1)
            p = batcher.submit(qi, qv)
            try:
                m = p.result(timeout=10.0)
            except Exception as e:   # any failure is a dropped request
                with lock:
                    failures.append(repr(e))
                continue
            with lock:
                answers.append((qi, qv, np.float32(m), p.model_round))

    threads = [threading.Thread(target=client, args=(s,), daemon=True)
               for s in range(4)]
    for t in threads:
        t.start()
    for gen in (20, 30, 40):   # three swaps under sustained traffic
        time.sleep(0.15)
        gens[gen] = rng.standard_normal(D).astype(np.float32)
        _save_model(tmp_path, gens[gen], gen, gap=1e-4)
    time.sleep(0.3)
    stop.set()
    for t in threads:
        t.join(10)
    watcher.stop()
    assert failures == []
    assert watcher.swaps_total == 3
    assert len(answers) > 20
    rounds_seen = {r for _, _, _, r in answers}
    assert len(rounds_seen) >= 2, "no traffic spanned a swap"
    for qi, qv, m, r in answers:
        assert r in gens, f"answered by unknown generation {r}"
        _assert_margin(m, gens[r], qi, qv)
    # post-drain: bit-identical to a cold restart on the newest ckpt
    cold = serving.BatchScorer(D, dtype=np.float32,
                               buckets=scorer.buckets, max_nnz=8)
    import jax

    w_cold = jax.device_put(gens[40])
    queries = _rand_queries(rng, 4)
    idx, val, hot = scorer.assemble(queries, 4)
    np.testing.assert_array_equal(
        np.asarray(scorer.score(slots.current()[0], idx, val, hot)),
        np.asarray(cold.score(w_cold, idx, val, hot)))
    batcher.stop()
    assert batcher.failed_total == 0
    assert tele_schema.check_file(str(bus)) == []


# --- the chaos pin: serving through an elastic shrink ------------------------


@pytest.mark.slow
def test_serving_survives_elastic_shrink_of_trainer(tmp_path,
                                                    monkeypatch):
    """A real 2-process toy gang (tests/_gang_worker.py) trains in the
    background under the elastic supervisor; worker 1 is SIGKILLed
    mid-run and the gang shrinks to the survivor — while an in-process
    serving stack pointed at the same checkpoint directory answers
    queries continuously.  Acceptance: zero failed queries end to end,
    at least one hot-swap during the outage window, and the final
    answers match the survivor's final checkpoint."""
    from _faults import Fault, FaultPlan, checkpoint_at_least, sigkill
    from cocoa_tpu import elastic

    monkeypatch.setenv(
        "PYTHONPATH",
        f"{ROOT}{os.pathsep}{TESTS}{os.pathsep}"
        f"{os.environ.get('PYTHONPATH', '')}")
    monkeypatch.setenv("XLA_FLAGS", " ".join(
        f for f in os.environ.get("XLA_FLAGS", "").split()
        if "host_platform_device_count" not in f))
    ck = tmp_path / "ck"
    k = 4
    plan = FaultPlan(
        Fault(generation=0, actions=(sigkill(1),),
              trigger=checkpoint_at_least(ck, "ToyGang", 5),
              name="kill-worker-1"),
    )
    gang_argv = [f"--chkptDir={ck}", f"--numSplits={k}",
                 "--numRounds=20", "--chkptIter=5",
                 "--stepSeconds=0.1"]
    rc_box = {}

    def run_gang():
        rc_box["rc"] = elastic.supervise(
            gang_argv, 2, module="_gang_worker", max_restarts=3,
            poll_s=0.05, num_splits=k, shrink="now",
            backoff_base_s=0.2, on_generation=plan.on_generation)

    gang = threading.Thread(target=run_gang, daemon=True)
    gang.start()
    # serve the toy model (w has shape (k,)) from the same directory
    assert serving.wait_for_model(str(ck), "ToyGang",
                                  timeout_s=60.0) is not None
    w0, info = serving.load_model(ckpt_lib.latest(str(ck), "ToyGang"))
    slots = serving.ModelSlots(w0, info, dtype=np.float32)
    scorer = serving.BatchScorer(k, dtype=np.float32, buckets=(4,),
                                 max_nnz=k)
    scorer.warmup(slots.current()[0])
    batcher = serving.MicroBatcher(scorer, slots, sla_s=0.02,
                                   algorithm="ToyGang")
    watcher = serving.SwapWatcher(slots, str(ck), "ToyGang",
                                  poll_s=0.05).start()
    failures = []
    n_answered = 0
    qi = np.arange(k, dtype=np.int32)
    qv = np.ones(k)
    while gang.is_alive():
        try:
            m = batcher.score_sync(qi, qv, timeout=10.0)
            assert np.isfinite(m)
            n_answered += 1
        except Exception as e:
            failures.append(repr(e))
        time.sleep(0.02)
    gang.join(120)
    plan.join()
    assert rc_box.get("rc") == 0
    assert plan.errors == []
    assert plan.fired == ["kill-worker-1"]
    assert failures == [], f"queries failed during the shrink: " \
                           f"{failures[:3]}"
    assert n_answered > 10
    assert watcher.swaps_total >= 1, "no hot-swap reached the server"
    # drain the final generation in, then check the served sum equals
    # the survivor's final checkpoint state
    deadline = time.monotonic() + 30.0
    meta, w_final, _ = ckpt_lib.load(ckpt_lib.latest(str(ck),
                                                     "ToyGang"))
    assert meta["round"] == 20
    while time.monotonic() < deadline:
        if slots.info.round == 20:
            break
        time.sleep(0.05)
    assert slots.info.round == 20
    got = np.float32(batcher.score_sync(qi, qv, timeout=10.0))
    # bit-identical to a cold restart on the survivor's final state:
    # same compiled path, same inputs, same model bytes
    import jax

    cold = serving.BatchScorer(k, dtype=np.float32, buckets=(4,),
                               max_nnz=k)
    ci, cv, ch = cold.assemble([(qi, qv)], 4)
    expect = np.asarray(cold.score(
        jax.device_put(np.asarray(w_final, np.float32)), ci, cv, ch))[0]
    np.testing.assert_array_equal(got, np.float32(expect))
    watcher.stop()
    batcher.stop()


# --- low-precision serving (--serveDtype, docs/DESIGN.md §20) ----------------


def _quant_stack(ck, serve_dtype, calibration=None, flip_guard=None,
                 hot_ids=None, buckets=(4, 16)):
    w, info = serving.load_model(ckpt_lib.latest(str(ck), "CoCoA+"))
    slots = serving.ModelSlots(w, info, dtype=serve_dtype,
                               calibration=calibration,
                               flip_guard=flip_guard)
    scorer = serving.BatchScorer(D, dtype=serve_dtype, buckets=buckets,
                                 max_nnz=8, hot_ids=hot_ids)
    w_dev, scale, _ = slots.current()
    scorer.warmup(w_dev, scale)
    return slots, scorer


def test_quantize_round_trip_bounds():
    """Packed-form round trips: bf16 dequantizes EXACTLY to the bf16
    image of w (truncation is the only loss), int8 stays within half a
    scale step, and the zero model takes the guard scale instead of a
    divide-by-zero."""
    import ml_dtypes

    from cocoa_tpu.serving import quantize

    rng = np.random.default_rng(5)
    w = (rng.standard_normal(101) * 3.0).astype(np.float32)  # odd: padding
    qm = quantize.quantize(w, "bf16")
    assert qm.scale is None and qm.packed.dtype == np.uint32
    assert qm.packed.shape == (51,)
    deq = quantize.dequantize(qm, 101)
    np.testing.assert_array_equal(
        deq, w.astype(ml_dtypes.bfloat16).astype(np.float32))
    assert np.all(np.abs(deq - w) <= np.abs(w) * 2.0 ** -8)
    qm8 = quantize.quantize(w, "int8")
    assert qm8.packed.dtype == np.int32 and qm8.packed.shape == (26,)
    assert np.isclose(qm8.scale, np.abs(w).max() / 127.0, rtol=1e-6)
    deq8 = quantize.dequantize(qm8, 101)
    assert np.all(np.abs(deq8 - w) <= qm8.scale / 2 + 1e-7)
    qz = quantize.quantize(np.zeros(8, np.float32), "int8")
    assert qz.scale == 1.0
    np.testing.assert_array_equal(quantize.dequantize(qz, 8), 0.0)


def test_quantized_scorer_matches_dequantized_model(tmp_path):
    """bf16/int8 compiled margins equal the margins of the DEQUANTIZED
    model through the f64 reference — quantization is weights-only, the
    query side never narrows."""
    from cocoa_tpu.serving import quantize

    rng = np.random.default_rng(6)
    w32 = rng.standard_normal(D).astype(np.float32)
    _save_model(tmp_path, w32, 10)
    queries = _rand_queries(rng, 5)
    for sd in ("bf16", "int8"):
        slots, scorer = _quant_stack(tmp_path, sd)
        assert slots.served_dtype == sd   # no calibration -> no fallback
        wq = quantize.dequantize(quantize.quantize(w32, sd), D)
        w_dev, scale, _ = slots.current()
        idx, val, hot = scorer.assemble(queries, 8)
        out = np.asarray(scorer.score(w_dev, idx, val, hot, scale))
        for r, (qi, qv) in enumerate(queries):
            _assert_margin(out[r], wq, qi, qv)


def test_forced_fallback_bit_exact_to_f32_control(tmp_path):
    """flip_guard=0.0 forces the certificate to cross on every publish:
    the stack serves the f32 form, and its margins are BITWISE equal to
    a --serveDtype=f32 control — fallback is a normal f32 publish
    through the same warmed executable, not a degraded mode."""
    rng = np.random.default_rng(7)
    w32 = rng.standard_normal(D).astype(np.float32)
    _save_model(tmp_path, w32, 10)
    calib = serving.CalibrationBuffer(D, max_nnz=8, seed=3)
    slots, scorer = _quant_stack(tmp_path, "bf16", calibration=calib,
                                 flip_guard=0.0)
    assert slots.served_dtype == "f32"
    assert slots.fallbacks_total == 1
    assert slots.last_bound is not None and slots.last_bound >= 0.0
    ctrl_slots, ctrl_scorer, ctrl_batcher = _serving_stack(tmp_path)
    queries = _rand_queries(rng, 6)
    idx, val, hot = scorer.assemble(queries, 16)
    w_dev, scale, _ = slots.current()
    assert scale is None and w_dev.dtype == np.dtype(np.float32)
    out = np.asarray(scorer.score(w_dev, idx, val, hot))
    ctrl = np.asarray(ctrl_scorer.score(ctrl_slots.current()[0],
                                        idx, val, hot))
    np.testing.assert_array_equal(out, ctrl)
    ctrl_batcher.stop()


def test_quantized_hot_panel_duplicate_ids(tmp_path):
    """The np.add.at duplicate-accumulation pin holds on the QUANTIZED
    hot panel: a --hotCols bf16 server answers the same margins as a
    plain bf16 one, both equal to the dequantized-model reference."""
    from cocoa_tpu.serving import quantize

    w32 = np.linspace(-1, 1, D).astype(np.float32)
    wq = quantize.dequantize(quantize.quantize(w32, "bf16"), D)
    _save_model(tmp_path, w32, 10)
    qi, qv = serving.parse_query("3:1.0 3:2.0 7:1.0", D, 8)
    outs = []
    for ids in (None, np.array([2, 5], np.int64)):
        slots, scorer = _quant_stack(tmp_path, "bf16", hot_ids=ids,
                                     buckets=(4,))
        w_dev, scale, _ = slots.current()
        idx, val, hot = scorer.assemble([(qi, qv)], 4)
        outs.append(np.asarray(scorer.score(w_dev, idx, val, hot,
                                            scale))[0])
    np.testing.assert_allclose(outs[0], outs[1], rtol=1e-6)
    _assert_margin(outs[0], wq, qi, qv)   # duplicates summed, not last


def test_quantized_swaps_never_recompile_and_fallback_publishes(
        tmp_path, bus):
    """Three int8 generations: certified publish, certified swap, then
    a certificate-crossing swap that falls back to the f32 form — ZERO
    compiles after warmup (the fallback form is warmed up front), and
    every publish emits a schema-valid model_quantize event."""
    rng = np.random.default_rng(8)
    w1 = (rng.standard_normal(D) + 2.0).astype(np.float32)
    _save_model(tmp_path, w1, 10, gap=1e-3)
    # capacity 8: the 8 recorded queries displace the synthetic warmup
    # seeds, so the certificate is bound over exactly these margins
    calib = serving.CalibrationBuffer(D, max_nnz=8, capacity=8, seed=4)
    # single-feature unit queries: every calibrated |margin| is |w_j|
    # (about 2), far above an int8 bound of a few centi-units
    for j in range(8):
        calib.record(np.array([j], np.int32),
                     np.array([1.0], np.float32))
    with sanitize.watch_compiles() as compiles:
        slots, scorer = _quant_stack(tmp_path, "int8",
                                     calibration=calib)
        n_warm = len([c for c in compiles
                      if "serve_margins" in c.name])
        # two forms (int8-packed + f32 fallback) per bucket
        assert n_warm == 2 * len(scorer.buckets)
        assert slots.served_dtype == "int8"
        watcher = serving.SwapWatcher(slots, str(tmp_path), "CoCoA+")
        _save_model(tmp_path, (w1 * 0.9).astype(np.float32), 20,
                    gap=1e-4)
        assert watcher.poll_once()
        assert slots.served_dtype == "int8"
        w_dev, scale, _ = slots.current()
        idx, val, hot = scorer.assemble(_rand_queries(rng, 3), 4)
        np.asarray(scorer.score(w_dev, idx, val, hot, scale))
        # a near-zero-margin calibration query drops the weakest
        # calibrated |margin| under the bound: the next publish must
        # fall back to f32 WITHOUT compiling anything
        calib.record(np.array([0], np.int32),
                     np.array([1e-6], np.float32))
        _save_model(tmp_path, (w1 * 0.8).astype(np.float32), 30,
                    gap=1e-5)
        assert watcher.poll_once()
        assert slots.served_dtype == "f32"
        assert slots.fallbacks_total == 1
        w_dev, scale, _ = slots.current()
        assert scale is None
        np.asarray(scorer.score(w_dev, idx, val, hot))
        total = len([c for c in compiles
                     if "serve_margins" in c.name])
    assert total == n_warm, (
        f"quantized swaps recompiled: {total} vs warmup {n_warm}")
    assert watcher.swaps_total == 2
    evs = [e for e in _read_events(bus)
           if e["event"] == "model_quantize"]
    assert [e["served"] for e in evs] == ["int8", "int8", "f32"]
    assert [e["fallback"] for e in evs] == [0, 0, 1]
    assert evs[-1]["serve_dtype"] == "int8"
    assert all(e["calib_n"] > 0 and e["bound"] is not None
               for e in evs)
    assert tele_schema.check_file(str(bus)) == []


def test_scorer_and_batcher_reject_form_mismatch(tmp_path):
    """Direct construction with mismatched dtypes is rejected with the
    numbers at every seam: batcher ctor, score() form check, and the
    int8 scale-pairing check."""
    _save_model(tmp_path, np.linspace(-1, 1, D).astype(np.float32), 10)
    w, info = serving.load_model(ckpt_lib.latest(str(tmp_path),
                                                 "CoCoA+"))
    slots_bf16 = serving.ModelSlots(w, info, dtype="bf16")
    scorer_f32 = serving.BatchScorer(D, dtype="f32", buckets=(4,),
                                     max_nnz=8)
    with pytest.raises(ValueError, match="serve dtype mismatch"):
        serving.MicroBatcher(scorer_f32, slots_bf16)
    idx, val, hot = scorer_f32.assemble([], 4)
    with pytest.raises(serving.QueryError,
                       match=r"model form mismatch.*uint32"):
        scorer_f32.score(slots_bf16.current()[0], idx, val, hot)
    slots_i8 = serving.ModelSlots(w, info, dtype="int8")
    scorer_i8 = serving.BatchScorer(D, dtype="int8", buckets=(4,),
                                    max_nnz=8)
    w_dev, scale, _ = slots_i8.current()
    with pytest.raises(serving.QueryError, match="scale mismatch"):
        scorer_i8.score(w_dev, idx, val, hot)       # dropped the scale
    # the f32 fallback form must NOT carry a scale
    import jax

    w_f32_dev = jax.device_put(np.asarray(w, np.float32))
    with pytest.raises(serving.QueryError, match="scale mismatch"):
        scorer_i8.score(w_f32_dev, idx, val, hot,
                        scale=np.float32(1.0))


@pytest.mark.slow
def test_quantized_swap_under_sustained_traffic(tmp_path, bus):
    """The PR-13 drops-nothing guarantee holds under --serveDtype:
    sustained traffic through the micro-batcher while generations swap
    (quantize + certify in the publish path), zero failed queries, and
    the final answers match the dequantized final model."""
    from cocoa_tpu.serving import quantize

    rng = np.random.default_rng(9)
    w = (rng.standard_normal(D) + 1.5).astype(np.float32)
    _save_model(tmp_path, w, 10, gap=1e-3)
    calib = serving.CalibrationBuffer(D, max_nnz=8, seed=5)
    w0, info = serving.load_model(ckpt_lib.latest(str(tmp_path),
                                                  "CoCoA+"))
    # flip_guard=1.0 pins the certificate OPEN for this test: client
    # queries are random, and a chance near-zero margin in the
    # calibration ring would trigger a legitimate fallback — correct
    # behavior, but this test pins the quantized traffic path, not the
    # certificate policy (covered above)
    slots = serving.ModelSlots(w0, info, dtype="bf16",
                               calibration=calib, flip_guard=1.0)
    scorer = serving.BatchScorer(D, dtype="bf16", buckets=(4, 16),
                                 max_nnz=8)
    w_dev, scale, _ = slots.current()
    scorer.warmup(w_dev, scale)
    batcher = serving.MicroBatcher(scorer, slots, sla_s=0.02,
                                   calibration=calib)
    watcher = serving.SwapWatcher(slots, str(tmp_path), "CoCoA+",
                                  poll_s=0.02).start()
    stop = threading.Event()
    failures = []
    answered = [0]

    def client(seed):
        crng = np.random.default_rng(seed)
        while not stop.is_set():
            n = int(crng.integers(1, 9))
            qi = np.sort(crng.choice(D, size=n,
                                     replace=False)).astype(np.int32)
            qv = crng.standard_normal(n)
            try:
                batcher.score_sync(qi, qv, timeout=10.0)
                answered[0] += 1
            except Exception as e:   # noqa: BLE001 - recorded, asserted
                failures.append(repr(e))

    threads = [threading.Thread(target=client, args=(s,), daemon=True)
               for s in range(3)]
    for t in threads:
        t.start()
    w_gen = w
    for gen in range(3):
        time.sleep(0.3)
        w_gen = (w_gen * 0.9).astype(np.float32)
        _save_model(tmp_path, w_gen, 20 + 10 * gen, gap=1e-4)
    deadline = time.monotonic() + 20.0
    while time.monotonic() < deadline and slots.info.round != 40:
        time.sleep(0.05)
    stop.set()
    for t in threads:
        t.join(10)
    watcher.stop()
    assert failures == [], f"queries failed: {failures[:3]}"
    assert answered[0] > 10
    assert slots.info.round == 40
    assert slots.served_dtype == "bf16"
    wq = quantize.dequantize(quantize.quantize(w_gen, "bf16"), D)
    qi, qv = serving.parse_query("1:1.0 5:-2.0", D, 8)
    got = batcher.score_sync(qi, qv, timeout=10.0)
    _assert_margin(got, wq, qi, qv)
    batcher.stop()


def test_quantize_metrics_families_rendered(tmp_path):
    """model_quantize events drive the two certificate families; runs
    that never quantize must not render them."""
    from cocoa_tpu.telemetry.metrics import MetricsWriter

    path = str(tmp_path / "m.prom")
    wtr = MetricsWriter(path)
    base = {"seq": 1, "pid": 1, "ts": 1000.0, "algorithm": "serve",
            "serve_dtype": "bf16", "calib_n": 64, "scale": None,
            "event": "model_quantize"}
    wtr({**base, "served": "bf16", "round": 10, "swap_seq": 1,
         "bound": 0.01, "flips": 0, "fallback": 0})
    text = open(path).read()
    assert "cocoa_serve_margin_error_bound 0.01" in text
    assert "cocoa_serve_dtype_fallbacks_total 0" in text
    wtr({**base, "served": "f32", "round": 11, "swap_seq": 2,
         "bound": 0.5, "flips": 3, "fallback": 1})
    text = open(path).read()
    assert "cocoa_serve_dtype_fallbacks_total 1" in text
    assert "cocoa_serve_margin_error_bound 0.5" in text
    # training-only runs never render the quantization families
    clean = str(tmp_path / "clean.prom")
    MetricsWriter(clean)
    assert "cocoa_serve_margin_error_bound" not in open(clean).read()
    assert "cocoa_serve_dtype_fallbacks" not in open(clean).read()


# --- fleet serving: catalogue scoring, routing, shedding (§21) ---------------


def _catalogue_stack(ck, n_tenants, buckets=(4, 16), max_nnz=8,
                     sla_s=0.05, algorithm="CoCoA+"):
    """A served (T, d) catalogue: one compiled scorer, tenant rows
    gathered per query — the fleet replica's in-process core."""
    w, info = serving.load_model(ckpt_lib.latest(str(ck), algorithm))
    slots = serving.ModelSlots(w, info, dtype=np.float32)
    scorer = serving.BatchScorer(D, dtype=np.float32, buckets=buckets,
                                 max_nnz=max_nnz, n_tenants=n_tenants)
    scorer.warmup(slots.current()[0])
    batcher = serving.MicroBatcher(scorer, slots, sla_s=sla_s,
                                   algorithm=algorithm)
    return slots, scorer, batcher


def _start_server(batcher, n_tenants=None):
    srv = serving.MarginServer(batcher, D, 8, port=0,
                               n_tenants=n_tenants)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


def _ask(addr, line):
    with socket.create_connection(addr, timeout=10) as s:
        s.sendall((line + "\n").encode())
        return json.loads(s.makefile("rb").readline())


def test_catalogue_bit_identical_to_single_tenant_servers(tmp_path):
    """The fleet correctness pin: a (T, d) catalogue answers every
    tenant BIT-identically to T independent single-tenant servers —
    the flat tenant-gather reads the same f32 values in the same
    reduction order as the 1-D gather — including across a mid-run
    catalogue hot-swap, with one compile per bucket regardless of T."""
    T = 3
    rng = np.random.default_rng(5)
    W1 = rng.standard_normal((T, D)).astype(np.float32)
    cat = tmp_path / "cat"
    cat.mkdir()
    _save_model(cat, W1, 10, gap=1e-3)
    solo_dirs = []
    for t in range(T):
        dt = tmp_path / f"solo{t}"
        dt.mkdir()
        _save_model(dt, W1[t], 10, gap=1e-3)
        solo_dirs.append(dt)
    with sanitize.watch_compiles() as compiles:
        cat_slots, cat_scorer, cat_batcher = _catalogue_stack(cat, T)
        n_warm = len([c for c in compiles
                      if "serve_margins" in c.name])
        # the tenant dim rides the SAME bucket executables: T models,
        # still one compile per (bucket, dtype)
        assert n_warm == len(cat_scorer.buckets) == 2
        controls = [_serving_stack(dt) for dt in solo_dirs]
        queries = _rand_queries(rng, 6)

        def compare_all():
            for t in range(T):
                for qi, qv in queries:
                    a = cat_batcher.score_sync(qi, qv, timeout=10.0,
                                               tenant=t)
                    b = controls[t][2].score_sync(qi, qv, timeout=10.0)
                    assert a == b, (t, a, b)

        compare_all()
        # mid-run catalogue hot-swap: one (T, d) generation vs T solo
        # swaps — still bit-identical, still zero new compiles
        W2 = (W1 * 0.7 + 1.0).astype(np.float32)
        _save_model(cat, W2, 20, gap=1e-4)
        assert serving.SwapWatcher(cat_slots, str(cat),
                                   "CoCoA+").poll_once()
        for t in range(T):
            _save_model(solo_dirs[t], W2[t], 20, gap=1e-4)
            assert serving.SwapWatcher(controls[t][0],
                                       str(solo_dirs[t]),
                                       "CoCoA+").poll_once()
        compare_all()
        cat_total = len([c for c in compiles
                         if "serve_margins" in c.name])
    # the controls compiled their own 1-D executables (2 buckets × T
    # would be 6 more); the CATALOGUE added none after warmup
    assert cat_total == n_warm + 2 * T
    cat_batcher.stop()
    for c in controls:
        c[2].stop()


def test_catalogue_tenant_protocol_rejections(tmp_path):
    """Every tenant-prefix misuse is rejected with the numbers, per
    line, without touching the batcher."""
    T = 3
    W = np.arange(T * D, dtype=np.float32).reshape(T, D)
    _save_model(tmp_path, W, 7)
    slots, scorer, batcher = _catalogue_stack(tmp_path, T)
    srv = serving.MarginServer(batcher, D, 8, port=0, n_tenants=T)
    try:
        ok = srv.answer_line("tenant=1;2:1.0")
        assert ok["tenant"] == 1 and ok["round"] == 7
        _assert_margin(ok["margin"], W[1], [1], [1.0])
        # no prefix on a catalogue server
        r = srv.answer_line("2:1.0")
        assert "catalogue of 3 tenant models" in r["error"]
        # out-of-range id, with the numbers
        r = srv.answer_line("tenant=3;2:1.0")
        assert "tenant 3 out of range" in r["error"]
        assert "3 tenants" in r["error"]
        # malformed id
        r = srv.answer_line("tenant=x;2:1.0")
        assert "malformed tenant prefix" in r["error"]
        # prefix without a query
        r = srv.answer_line("tenant=1")
        assert "without a query" in r["error"]
        # a per-query parse error inside a tenant batch fails only
        # itself, and every answer carries the tenant
        rs = srv.answer_line("tenant=2;2:1.0;99:1.0")
        assert rs[0]["tenant"] == 2 and "feature id 99" in \
            rs[1]["error"]
    finally:
        srv.close()
        batcher.stop()
    # the prefix on a SINGLE-model server points at the catalogue docs
    _save_model(tmp_path / "solo", np.zeros(D, np.float32), 7)
    slots1, scorer1, batcher1 = _serving_stack(tmp_path / "solo")
    srv1 = serving.MarginServer(batcher1, D, 8, port=0)
    try:
        r = srv1.answer_line("tenant=0;2:1.0")
        assert "single-model server" in r["error"]
    finally:
        srv1.close()
        batcher1.stop()


def test_scorer_tenant_vector_mismatch_rejected(tmp_path):
    """A catalogue scorer without a tenant vector (and vice versa) is a
    dispatch-shape bug — rejected with the numbers, not compiled."""
    T = 2
    _save_model(tmp_path, np.zeros((T, D), np.float32), 7)
    slots, scorer, batcher = _catalogue_stack(tmp_path, T)
    idx, val, hot = scorer.assemble([], 4)
    with pytest.raises(serving.QueryError, match="catalogue of 2"):
        scorer.score(slots.current()[0], idx, val, hot, None, None)
    batcher.stop()
    _save_model(tmp_path / "solo", np.zeros(D, np.float32), 7)
    slots1, scorer1, batcher1 = _serving_stack(tmp_path / "solo")
    idx, val, hot = scorer1.assemble([], 4)
    with pytest.raises(serving.QueryError, match="single model"):
        scorer1.score(slots1.current()[0], idx, val, hot, None,
                      np.zeros(4, np.int32))
    batcher1.stop()


def test_fleet_router_routes_requeues_and_sheds(tmp_path, bus):
    """The fleet chaos pin, in-process: two catalogue replicas behind
    the router; a killed replica's lines requeue (zero failed), a
    respawned one rejoins, overload sheds with a typed event — and the
    gauges render."""
    from cocoa_tpu.serving.router import Router

    T = 4
    rng = np.random.default_rng(11)
    W = rng.standard_normal((T, D)).astype(np.float32)
    cat = tmp_path / "cat"
    cat.mkdir()
    _save_model(cat, W, 10, gap=1e-3)
    stacks = [_catalogue_stack(cat, T) for _ in range(2)]
    servers = [_start_server(s[2], n_tenants=T) for s in stacks]
    router = Router([(f"r{i}", srv.address)
                     for i, srv in enumerate(servers)],
                    sla_s=0.5, route="tenant")
    threading.Thread(target=router.serve_forever, daemon=True).start()
    router.emit_initial_state()
    revive = None
    try:
        queries = _rand_queries(rng, 4)
        for t in range(T):
            for qi, qv in queries:
                line = (f"tenant={t};"
                        + " ".join(f"{int(i) + 1}:{float(v)!r}"
                                   for i, v in zip(qi, qv)))
                got = _ask(router.address, line)
                want = stacks[0][2].score_sync(qi, qv, timeout=10.0,
                                               tenant=t)
                assert got["margin"] == want and got["tenant"] == t
        assert router.replicas_live() == 2
        # kill r0 the way a SIGKILL looks from the router: listener
        # gone, pooled connections broken
        servers[0]._tcp.shutdown()
        servers[0]._tcp.server_close()
        router.replicas[0].close_all()
        for t in range(T):   # tenant-affine homes to r0 for t%2==0
            r = _ask(router.address, f"tenant={t};2:1.0")
            assert "margin" in r, r
        assert router.requeue_total >= 1
        assert router.failed_total == 0
        assert router.replicas_live() == 1
        # revive under the old name on a new port (the fleet monitor's
        # respawn path)
        revive = _catalogue_stack(cat, T)
        srv_new = _start_server(revive[2], n_tenants=T)
        servers.append(srv_new)
        router.mark_live("r0", srv_new.address)
        assert router.replicas_live() == 2
        assert "margin" in _ask(router.address, "tenant=0;2:1.0")
        # overload: every live replica projects past the shed budget
        for rep in router.replicas:
            rep.ewma_s, rep.inflight = 10.0, 9
        shed = _ask(router.address, "tenant=1;2:1.0")
        assert shed.get("shed") is True and "shed:" in shed["error"]
        for rep in router.replicas:
            rep.ewma_s, rep.inflight = 0.0, 0
    finally:
        router.stop()
        router.close()
        for srv in servers:
            srv.close()
        for s in stacks + ([revive] if revive else []):
            s[2].stop()
    events = _read_events(bus)
    assert tele_schema.check_file(str(bus)) == []
    kinds = [e["event"] for e in events]
    assert "serve_shed" in kinds and "replica_state" in kinds
    dead = [e for e in events if e["event"] == "replica_state"
            and e["state"] == "dead"]
    requeues = [e for e in events if e["event"] == "replica_state"
                and e["state"] == "requeue"]
    lives = [e for e in events if e["event"] == "replica_state"
             and e["state"] == "live"]
    assert dead and requeues and len(lives) >= 3   # 2 initial + revive
    assert all(e["requeued"] == 1 for e in requeues)
    shed_ev = [e for e in events if e["event"] == "serve_shed"][0]
    assert shed_ev["route"] == "tenant" and shed_ev["tenant"] == 1
    assert shed_ev["est_s"] > shed_ev["sla_s"]


def test_fleet_metrics_families_rendered(tmp_path):
    """serve_shed / replica_state drive the three fleet families;
    single-process serves must not render them."""
    from cocoa_tpu.telemetry.metrics import MetricsWriter

    path = str(tmp_path / "m.prom")
    wtr = MetricsWriter(path)
    base = {"seq": 1, "pid": 1, "ts": 1000.0, "algorithm": "serve"}
    wtr({**base, "event": "replica_state", "replica": "r0",
         "state": "live", "replicas_live": 2, "requeued": 0})
    wtr({**base, "event": "replica_state", "replica": "r0",
         "state": "requeue", "replicas_live": 1, "requeued": 1})
    wtr({**base, "event": "serve_shed", "route": "rr", "tenant": None,
         "inflight": 9, "est_s": 1.0, "sla_s": 0.05})
    text = open(path).read()
    for needle in ("cocoa_serve_replicas_live 1",
                   "cocoa_serve_shed_total 1",
                   "cocoa_serve_requeue_total 1"):
        assert needle in text, f"{needle} missing from:\n{text}"
    clean = str(tmp_path / "clean.prom")
    MetricsWriter(clean)
    assert "cocoa_serve_replicas_live" not in open(clean).read()
    assert "cocoa_serve_shed_total" not in open(clean).read()
