"""bf16: certify or retire (VERDICT r5 weak #5).

README advertises ``--dtype=bfloat16``; docs/DESIGN.md §6 predicts a 1e-4
duality gap CANNOT be certified in bf16 (the dual objective's Σα/n
accumulation and the primal−dual cancellation sit below bf16's ~2^-8
relative resolution).  These tests measure that prediction — the bf16
trajectory's computed gap is quantization noise at 1e-4 scale (it reads
exactly 0.0 on some evals while the f64-recomputed gap of the same
iterate is ~20x the target) and the x-accumulated iterate itself stalls
above the target — and pin the consequence: gap-targeted bf16 runs are
REJECTED with the remedy, at the solver API and at the CLI.  Uncertified
(fixed-round) bf16 runs stay allowed; the fori_loop path runs them (the
Pallas kernels gate on itemsize == 4).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from cocoa_tpu.config import DebugParams, Params
from cocoa_tpu.data.sharding import shard_dataset
from cocoa_tpu.data.synth import synth_dense
from cocoa_tpu.evals import objectives
from cocoa_tpu.solvers import run_cocoa

K = 4
LAM = 1e-3
GAP_TARGET = 1e-4


@pytest.fixture(scope="module")
def dense_data():
    # big enough that 200 rounds drive the f32 gap well below where bf16
    # stalls, small enough for the fast suite
    return synth_dense(512, 32, seed=3)


def _run(data, dtype, rounds=200):
    ds = shard_dataset(data, k=K, layout="dense", dtype=dtype)
    p = Params(n=data.n, num_rounds=rounds, local_iters=32, lam=LAM)
    dbg = DebugParams(debug_iter=25, seed=0)
    w, a, traj = run_cocoa(ds, p, dbg, plus=True, quiet=True, math="fast")
    return w, a, traj


def _true_gap(data, w, alpha):
    """The exact duality gap of the iterate, recomputed in f64 — what the
    certificate claims to measure."""
    ds64 = shard_dataset(data, k=K, layout="dense", dtype=jnp.float64)
    _, gap, _ = objectives.evaluate(
        ds64, jnp.asarray(np.asarray(w, np.float64)),
        jnp.asarray(np.asarray(alpha, np.float64)), LAM)
    return float(gap)


def test_bf16_gap_certificate_is_noise_at_target_scale(dense_data):
    """The demo-config-shaped trajectory at --dtype=bfloat16 (x-accum):
    the bf16-COMPUTED gap disagrees with the f64-recomputed gap of the
    same state by more than the 1e-4 target (measured: it quantizes to
    exactly 0.0 on some evals — a spurious certificate), and the bf16
    iterate itself stalls above the target while the f32 twin keeps
    descending.  The f32 control's computed gap tracks its true gap to
    well under the target — the certificate is trustworthy exactly where
    the kernels run it."""
    w16, a16, traj16 = _run(dense_data, jnp.bfloat16)
    w32, a32, traj32 = _run(dense_data, jnp.float32)

    true16 = _true_gap(dense_data, w16, a16)
    true32 = _true_gap(dense_data, w32, a32)
    comp16 = float(traj16.records[-1].gap)
    comp32 = float(traj32.records[-1].gap)

    # f32: the computed certificate measures the true gap at target scale
    assert abs(comp32 - true32) < GAP_TARGET / 2
    # bf16: the computed certificate is off by MORE than the target —
    # a gap-targeted run would stop on rounding artifacts
    assert abs(comp16 - true16) > GAP_TARGET
    # and the x-accumulated bf16 iterate cannot reach the target anyway:
    # it stalls above both the target and the f32 twin's true gap
    assert true16 > GAP_TARGET
    assert true16 > true32


def test_bf16_gap_target_rejected(dense_data):
    """Gap-targeted bf16 runs are rejected with the remedy (the
    certificate they would stop on is unmeasurable — see above)."""
    ds = shard_dataset(dense_data, k=K, layout="dense", dtype=jnp.bfloat16)
    p = Params(n=dense_data.n, num_rounds=10, local_iters=8, lam=LAM)
    with pytest.raises(ValueError, match="bfloat16"):
        run_cocoa(ds, p, DebugParams(debug_iter=5, seed=0), plus=True,
                  quiet=True, math="fast", gap_target=GAP_TARGET)


def test_bf16_fixed_rounds_still_run(dense_data):
    """Uncertified bf16 runs stay allowed — storage-dtype experiments are
    legitimate; only the certificate claim is rejected."""
    w, a, traj = _run(dense_data, jnp.bfloat16, rounds=4)
    assert w.dtype == jnp.bfloat16
    assert len(traj.records) == 0 or np.isfinite(
        float(traj.records[-1].primal))


def _write_tiny_libsvm(path):
    rows = ["+1 1:0.5 3:1.0", "-1 2:0.25 4:0.5", "+1 1:0.75",
            "-1 3:0.5 4:0.25"] * 8
    path.write_text("\n".join(rows) + "\n")


def test_cli_rejects_bf16_gap_target(tmp_path, capsys):
    from cocoa_tpu import cli

    train = tmp_path / "tiny.dat"
    _write_tiny_libsvm(train)
    rc = cli.main([
        f"--trainFile={train}", "--numFeatures=4", "--numSplits=2",
        "--numRounds=4", "--localIterFrac=0.5", "--lambda=.01",
        "--justCoCoA=true", "--debugIter=2", "--dtype=bfloat16",
        "--gapTarget=1e-4", "--mesh=1",
    ])
    assert rc == 2
    assert "bfloat16" in capsys.readouterr().err
