"""Fast-math (margins decomposition) and Pallas kernel paths.

The fast inner loop is exactly equal in real arithmetic to the reference
order (x·w_step = margins0 + sig_eff·x·Δw — see ops/local_sdca.mode_factors);
floating point rounds differently, so trajectory equality is asserted loosely
while convergence properties are asserted exactly.  The Pallas kernel (run
in interpreter mode on CPU) must match the XLA fast path to near-machine
precision.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cocoa_tpu.config import DebugParams, Params
from cocoa_tpu.data.sharding import shard_dataset
from cocoa_tpu.ops.local_sdca import local_sdca, local_sdca_fast
from cocoa_tpu.ops.pallas_sdca import pallas_sdca_round
from cocoa_tpu.parallel import make_mesh
from cocoa_tpu.solvers import run_cocoa
from cocoa_tpu.utils.prng import sample_indices_per_shard


def _params(tiny_data, **kw):
    defaults = dict(n=tiny_data.n, num_rounds=10, local_iters=20, lam=0.01,
                    beta=1.0, gamma=1.0)
    defaults.update(kw)
    return Params(**defaults)


_DBG = DebugParams(debug_iter=-1, seed=0)


@pytest.mark.parametrize("mode,sigma", [("cocoa", 1.0), ("plus", 4.0), ("frozen", 1.0)])
@pytest.mark.parametrize("layout", ["dense", "sparse"])
def test_fast_kernel_close_to_exact(tiny_data, mode, sigma, layout):
    ds = shard_dataset(tiny_data, k=1, layout=layout, dtype=jnp.float64)
    shard = {k: v[0] for k, v in ds.shard_arrays().items()}
    rng = np.random.default_rng(1)
    d = tiny_data.num_features
    w = jnp.asarray(rng.normal(size=d) * 0.1)
    alpha = jnp.asarray(np.clip(rng.normal(size=tiny_data.n) * 0.3 + 0.3, 0, 1))
    idxs = jnp.asarray(
        sample_indices_per_shard(7, range(1, 2), 100, [tiny_data.n])[0, 0]
    )
    da_e, dw_e = local_sdca(w, alpha, shard, idxs, 0.01, tiny_data.n,
                            mode=mode, sigma=sigma)
    from cocoa_tpu.ops.rows import shard_margins

    m0 = shard_margins(w, shard)
    da_f, dw_f = local_sdca_fast(m0, alpha, shard, idxs, 0.01, tiny_data.n,
                                 jnp.zeros(d, dtype=jnp.float64),
                                 mode=mode, sigma=sigma)
    np.testing.assert_allclose(np.asarray(da_f), np.asarray(da_e),
                               rtol=1e-8, atol=1e-10)
    np.testing.assert_allclose(np.asarray(dw_f), np.asarray(dw_e),
                               rtol=1e-8, atol=1e-10)


@pytest.mark.parametrize("mode,sigma", [("cocoa", 1.0), ("plus", 4.0), ("frozen", 1.0)])
def test_pallas_interpret_matches_xla_fast(tiny_data, mode, sigma):
    k = 4
    ds = shard_dataset(tiny_data, k=k, layout="dense", dtype=jnp.float64)
    rng = np.random.default_rng(2)
    d = tiny_data.num_features
    w = jnp.asarray(rng.normal(size=d) * 0.1)
    alpha = jnp.asarray(
        np.clip(rng.normal(size=(k, ds.n_shard)) * 0.3 + 0.3, 0, 1)
    )
    idxs = jnp.asarray(
        sample_indices_per_shard(5, range(1, 2), 30, ds.counts)[:, 0, :]
    )
    dw_p, a_p = pallas_sdca_round(
        w, alpha, ds.X, ds.labels, ds.sq_norms, idxs, 0.01, tiny_data.n,
        mode=mode, sigma=sigma, interpret=True,
    )
    m0 = jnp.einsum("knd,d->kn", ds.X, w)
    for s in range(k):
        shard = {kk: v[s] for kk, v in ds.shard_arrays().items()}
        da, dw = local_sdca_fast(
            m0[s], alpha[s], shard, idxs[s], 0.01, tiny_data.n,
            jnp.zeros(d, dtype=jnp.float64), mode=mode, sigma=sigma,
        )
        # in-kernel margins reduce x·w in a different order than the
        # einsum the fast path precomputes — x64 agreement to ~1e-13
        np.testing.assert_allclose(np.asarray(dw_p[s]), np.asarray(dw),
                                   atol=1e-12)
        np.testing.assert_allclose(np.asarray(a_p[s] - alpha[s]),
                                   np.asarray(da), atol=1e-12)


@pytest.mark.slow
@pytest.mark.parametrize("mode,sigma", [("cocoa", 1.0), ("plus", 4.0), ("frozen", 1.0)])
def test_pallas_sparse_interpret_matches_xla_fast(tiny_data, mode, sigma):
    """The sparse (padded-CSR) kernel — in-kernel margins, SMEM feature
    addressing, lane-blocked w/Δw — must match the XLA fast path."""
    from cocoa_tpu.ops.pallas_sparse import pallas_sparse_sdca_round
    from cocoa_tpu.ops.rows import shard_margins

    k = 4
    ds = shard_dataset(tiny_data, k=k, layout="sparse", dtype=jnp.float64)
    rng = np.random.default_rng(4)
    d = ds.num_features
    w = jnp.asarray(rng.normal(size=d) * 0.1)
    alpha = jnp.asarray(
        np.clip(rng.normal(size=(k, ds.n_shard)) * 0.3 + 0.3, 0, 1)
    )
    idxs = jnp.asarray(
        sample_indices_per_shard(6, range(1, 2), 30, ds.counts)[:, 0, :]
    )
    dw_p, a_p = pallas_sparse_sdca_round(
        w, alpha, ds.sp_indices, ds.sp_values, ds.labels, ds.sq_norms,
        idxs, 0.01, tiny_data.n, mode=mode, sigma=sigma, interpret=True,
    )
    for s in range(k):
        shard = {kk: v[s] for kk, v in ds.shard_arrays().items()}
        m0 = shard_margins(w, shard)
        da, dw = local_sdca_fast(
            m0, alpha[s], shard, idxs[s], 0.01, tiny_data.n,
            jnp.zeros(d, dtype=jnp.float64), mode=mode, sigma=sigma,
        )
        np.testing.assert_allclose(np.asarray(dw_p[s]), np.asarray(dw),
                                   atol=1e-13)
        np.testing.assert_allclose(np.asarray(a_p[s] - alpha[s]),
                                   np.asarray(da), atol=1e-13)


@pytest.mark.slow
def test_pallas_sparse_solver_end_to_end_interpret(tiny_data):
    """Full CoCoA+ run through the sparse Pallas kernel (interpret mode,
    chunked driver) tracks the fori_loop fast path."""
    ds = shard_dataset(tiny_data, k=4, layout="sparse", dtype=jnp.float64)
    p = _params(tiny_data, num_rounds=15, local_iters=20)
    dbg = DebugParams(debug_iter=15, seed=0)
    w_f, a_f, traj_f = run_cocoa(ds, p, dbg, plus=True, quiet=True,
                                 math="fast", pallas=False, scan_chunk=5)
    w_p, a_p, traj_p = run_cocoa(ds, p, dbg, plus=True, quiet=True,
                                 math="fast", pallas=True, scan_chunk=5)
    np.testing.assert_allclose(np.asarray(w_p), np.asarray(w_f), atol=1e-10)
    np.testing.assert_allclose(np.asarray(a_p), np.asarray(a_f), atol=1e-10)


@pytest.mark.slow
@pytest.mark.parametrize("unroll", [1, 2, 4, 8])
def test_pallas_unroll_invariant(tiny_data, unroll):
    """The step-group size S is a pure DMA-batching knob: every S must
    produce the same (dw, alpha) to machine precision — same op sequence,
    XLA may fuse the unrolled body differently — including S ∤ H (the
    clamped inert tail)."""
    k = 2
    ds = shard_dataset(tiny_data, k=k, layout="dense", dtype=jnp.float64)
    rng = np.random.default_rng(3)
    d = tiny_data.num_features
    w = jnp.asarray(rng.normal(size=d) * 0.1)
    alpha = jnp.asarray(
        np.clip(rng.normal(size=(k, ds.n_shard)) * 0.3 + 0.3, 0, 1)
    )
    h = 27  # not divisible by any S > 1
    idxs = jnp.asarray(
        sample_indices_per_shard(9, range(1, 2), h, ds.counts)[:, 0, :]
    )
    kw = dict(mode="plus", sigma=2.0, interpret=True)
    dw_1, a_1 = pallas_sdca_round(
        w, alpha, ds.X, ds.labels, ds.sq_norms, idxs, 0.01, tiny_data.n,
        unroll=1, **kw,
    )
    dw_s, a_s = pallas_sdca_round(
        w, alpha, ds.X, ds.labels, ds.sq_norms, idxs, 0.01, tiny_data.n,
        unroll=unroll, **kw,
    )
    np.testing.assert_allclose(np.asarray(dw_s), np.asarray(dw_1),
                               rtol=0, atol=1e-13)
    np.testing.assert_allclose(np.asarray(a_s), np.asarray(a_1),
                               rtol=0, atol=1e-13)


@pytest.mark.parametrize("plus", [True, False])
def test_fast_solver_converges_like_exact(tiny_data, plus):
    ds = shard_dataset(tiny_data, k=4, layout="dense", dtype=jnp.float64)
    p = _params(tiny_data, num_rounds=40, local_iters=30)
    dbg = DebugParams(debug_iter=40, seed=0)
    _, _, traj_e = run_cocoa(ds, p, dbg, plus=plus, quiet=True)
    _, _, traj_f = run_cocoa(ds, p, dbg, plus=plus, quiet=True,
                             math="fast", pallas=False)
    gap_e = traj_e.records[-1].gap
    gap_f = traj_f.records[-1].gap
    assert gap_f == pytest.approx(gap_e, rel=1e-3)
    assert gap_f >= -1e-12


@pytest.mark.slow
def test_pallas_solver_end_to_end_interpret(tiny_data):
    """Full CoCoA+ run through the Pallas kernel (interpret mode, chunked
    driver, single-chip path) tracks the exact solver."""
    ds = shard_dataset(tiny_data, k=4, layout="dense", dtype=jnp.float64)
    p = _params(tiny_data, num_rounds=20, local_iters=20)
    dbg = DebugParams(debug_iter=20, seed=0)
    _, _, traj_e = run_cocoa(ds, p, dbg, plus=True, quiet=True)
    _, _, traj_p = run_cocoa(ds, p, dbg, plus=True, quiet=True,
                             math="fast", pallas=True, scan_chunk=5)
    assert traj_p.records[-1].gap == pytest.approx(traj_e.records[-1].gap,
                                                   rel=1e-3)


@pytest.mark.parametrize("scan", [0, 4])
def test_fast_math_on_mesh_without_pallas(tiny_data, scan):
    """math='fast' must work under shard_map on a real mesh (regression:
    the dw carry needs varying provenance), per-round and chunked."""
    k = 4
    mesh = make_mesh(k)
    ds_m = shard_dataset(tiny_data, k=k, layout="dense", dtype=jnp.float64, mesh=mesh)
    ds_l = shard_dataset(tiny_data, k=k, layout="dense", dtype=jnp.float64)
    p = _params(tiny_data, num_rounds=8)
    dbg = DebugParams(debug_iter=8, seed=0)
    _, _, tm = run_cocoa(ds_m, p, dbg, plus=True, mesh=mesh, quiet=True,
                         math="fast", pallas=False, scan_chunk=scan)
    _, _, tl = run_cocoa(ds_l, p, dbg, plus=True, quiet=True,
                         math="fast", pallas=False, scan_chunk=scan)
    assert tm.records[-1].gap == pytest.approx(tl.records[-1].gap, abs=1e-12)


@pytest.mark.slow
def test_pallas_mesh_per_round_driver_reroutes(tiny_data):
    """pallas on a mesh with scan_chunk=0 must not crash (regression: it is
    rerouted through the chunked driver)."""
    k = 4
    mesh = make_mesh(k)
    ds = shard_dataset(tiny_data, k=k, layout="dense", dtype=jnp.float64, mesh=mesh)
    p = _params(tiny_data, num_rounds=4)
    _, _, traj = run_cocoa(ds, p, DebugParams(debug_iter=4, seed=0), plus=True,
                           mesh=mesh, quiet=True, math="fast", pallas=True)
    assert traj.records[-1].gap is not None


def test_math_flag_validated(tiny_data):
    ds = shard_dataset(tiny_data, k=2, layout="dense", dtype=jnp.float64)
    with pytest.raises(ValueError, match="math"):
        run_cocoa(ds, _params(tiny_data), _DBG, plus=True, quiet=True,
                  math="fas")


@pytest.mark.slow
def test_pallas_mesh_equals_local(tiny_data):
    """Pallas kernel inside shard_map (4-device mesh) == single-chip path."""
    k = 4
    mesh = make_mesh(k)
    ds_m = shard_dataset(tiny_data, k=k, layout="dense", dtype=jnp.float64, mesh=mesh)
    ds_l = shard_dataset(tiny_data, k=k, layout="dense", dtype=jnp.float64)
    p = _params(tiny_data, num_rounds=8)
    dbg = DebugParams(debug_iter=8, seed=0)
    _, _, tm = run_cocoa(ds_m, p, dbg, plus=True, mesh=mesh, quiet=True,
                         math="fast", pallas=True, scan_chunk=4)
    _, _, tl = run_cocoa(ds_l, p, dbg, plus=True, quiet=True,
                         math="fast", pallas=True, scan_chunk=4)
    assert tm.records[-1].gap == pytest.approx(tl.records[-1].gap, abs=1e-12)


def test_pallas_requires_fast_math(tiny_data):
    ds = shard_dataset(tiny_data, k=2, layout="dense", dtype=jnp.float64)
    with pytest.raises(ValueError, match="fast"):
        run_cocoa(ds, _params(tiny_data), _DBG, plus=True, quiet=True,
                  math="exact", pallas=True)
