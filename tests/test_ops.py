"""Local-solver kernels vs the literal NumPy oracle (tests/oracle.py), in x64.

Given identical index sequences the JAX kernels must reproduce the reference
math bit-closely (1e-12) for every mode and both layouts.
"""

import jax.numpy as jnp
import numpy as np
import pytest

import oracle
from cocoa_tpu.data.sharding import shard_dataset
from cocoa_tpu.ops import local_sdca, local_sgd, subgradient_pass
from cocoa_tpu.utils.prng import sample_indices


def _one_shard(tiny_data, layout):
    ds = shard_dataset(tiny_data, k=1, layout=layout, dtype=jnp.float64)
    return {k: v[0] for k, v in ds.shard_arrays().items()}


def _setup(tiny_data):
    X = tiny_data.to_dense()
    y = tiny_data.labels
    n, d = X.shape
    rng = np.random.default_rng(3)
    w = rng.normal(size=d) * 0.1
    alpha = np.clip(rng.normal(size=n) * 0.3 + 0.3, 0.0, 1.0)
    idxs = sample_indices(seed=11, rounds=range(1, 2), h=150, n_local=n)[0]
    return X, y, w, alpha, idxs


@pytest.mark.parametrize("layout", ["dense", "sparse"])
@pytest.mark.parametrize(
    "mode,plus,sigma", [("cocoa", False, 1.0), ("plus", True, 4.0)]
)
def test_local_sdca_matches_oracle(tiny_data, layout, mode, plus, sigma):
    X, y, w, alpha, idxs = _setup(tiny_data)
    lam, n = 0.001, X.shape[0]
    da_o, dw_o = oracle.local_sdca(X, y, w, alpha, idxs, lam, n, plus, sigma)
    shard = _one_shard(tiny_data, layout)
    da, dw = local_sdca(
        jnp.asarray(w), jnp.asarray(alpha), shard, jnp.asarray(idxs),
        lam, n, mode=mode, sigma=sigma,
    )
    np.testing.assert_allclose(np.asarray(da), da_o, atol=1e-12)
    np.testing.assert_allclose(np.asarray(dw), dw_o, atol=1e-12)


@pytest.mark.parametrize("layout", ["dense", "sparse"])
def test_frozen_mode_matches_minibatch_cd_oracle(tiny_data, layout):
    X, y, w, alpha, idxs = _setup(tiny_data)
    lam, n, scaling = 0.001, X.shape[0], 0.25
    dw_o, alpha_scaled_o = oracle.minibatch_cd_partition(
        X, y, w, alpha, idxs, lam, n, scaling
    )
    shard = _one_shard(tiny_data, layout)
    da, dw = local_sdca(
        jnp.asarray(w), jnp.asarray(alpha), shard, jnp.asarray(idxs),
        lam, n, mode="frozen",
    )
    np.testing.assert_allclose(np.asarray(dw), dw_o, atol=1e-12)
    np.testing.assert_allclose(
        alpha + scaling * np.asarray(da), alpha_scaled_o, atol=1e-12
    )


@pytest.mark.parametrize("layout", ["dense", "sparse"])
@pytest.mark.parametrize("local", [True, False])
def test_local_sgd_matches_oracle(tiny_data, layout, local):
    X, y, w, _, idxs = _setup(tiny_data)
    lam, t_global = 0.001, 960.0  # (t-1)*H*K for some mid-run round
    dw_o = oracle.sgd_partition(X, y, w, idxs, lam, t_global, local)
    shard = _one_shard(tiny_data, layout)
    dw = local_sgd(jnp.asarray(w), shard, jnp.asarray(idxs), lam, t_global, local)
    np.testing.assert_allclose(np.asarray(dw), dw_o, atol=1e-12)


@pytest.mark.parametrize("layout", ["dense", "sparse"])
def test_subgradient_pass_matches_oracle(tiny_data, layout):
    X, y, w, _, _ = _setup(tiny_data)
    lam = 0.001
    dw_o = oracle.dist_gd_partition(X, y, w, lam)
    shard = _one_shard(tiny_data, layout)
    dw = subgradient_pass(jnp.asarray(w), shard, lam)
    np.testing.assert_allclose(np.asarray(dw), dw_o, atol=1e-12)


def test_alpha_stays_in_box(tiny_data):
    """Property: SDCA keeps every alpha in [0,1] (the dual box constraint)."""
    X, y, w, alpha, idxs = _setup(tiny_data)
    shard = _one_shard(tiny_data, "dense")
    da, _ = local_sdca(
        jnp.asarray(w), jnp.asarray(alpha), shard, jnp.asarray(idxs),
        0.001, X.shape[0], mode="cocoa",
    )
    final = alpha + np.asarray(da)
    assert np.all(final >= -1e-15) and np.all(final <= 1.0 + 1e-15)


def test_zero_row_qii_zero_sets_alpha_one(tiny_data):
    """Reference edge: qii == 0 (all-zero row) forces newAlpha = 1.0
    (CoCoA.scala:175-178) with a zero primal update."""
    import numpy as np

    from cocoa_tpu.data.libsvm import LibsvmData

    d = 4
    data = LibsvmData(
        labels=np.array([1.0, -1.0]),
        indptr=np.array([0, 0, 1]),   # row 0 empty
        indices=np.array([1], dtype=np.int32),
        values=np.array([2.0]),
        num_features=d,
    )
    ds = shard_dataset(data, k=1, layout="dense", dtype=jnp.float64)
    shard = {k: v[0] for k, v in ds.shard_arrays().items()}
    w = jnp.zeros(ds.num_features, dtype=jnp.float64)  # d padded to 8
    alpha = jnp.zeros(2, dtype=jnp.float64)
    idxs = jnp.asarray([0], dtype=jnp.int32)  # hit the empty row
    da, dw = local_sdca(w, alpha, shard, idxs, 0.5, 2, mode="cocoa")
    assert float(da[0]) == 1.0
    np.testing.assert_array_equal(np.asarray(dw), 0.0)
