"""Chaos suite: every recovery path under deterministic fault injection.

The fault harness (tests/_faults.py) drives the elastic supervisor's
hooks with scheduled kills / wedges / checkpoint corruption, so each
failure mode the supervisor claims to survive is pinned by a
reproducible test:

- shrink-to-survivors: a worker lost mid-run reforms the gang at P′ < P
  and the run completes bit-identically to an unfailed control.  The
  real-process toy-gang pair rides the slow marker purely for tier-1
  wall-clock budget (it runs on ANY jax — ``-m slow -k gang_`` — and in
  the CI chaos step, which also runs tests/chaos_smoke.py end to end);
  the real-TRAINING 2-process pin is additionally gated on a jax with
  multi-process CPU collectives like the rest of the repo's gang tests;
- checkpoint generations: a torn newest checkpoint falls back to the
  previous generation (validation-on-load), and the resumed run still
  reproduces the uninterrupted trajectory exactly;
- bounded KV ops: a peer that never publishes fails in bounded time
  with the peer/key named, not a silent 10-minute hang;
- restart backoff: exponential with seeded jitter, capped, reset on
  progress.
"""

import json
import os
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from _faults import (Fault, FaultPlan, checkpoint_at_least, sigkill,
                     truncate_newest_checkpoint)
from cocoa_tpu import checkpoint as ckpt_lib
from cocoa_tpu import elastic
from cocoa_tpu.parallel import distributed
from cocoa_tpu.telemetry import events as tele_events
from cocoa_tpu.telemetry import schema as tele_schema

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TESTS = os.path.dirname(os.path.abspath(__file__))


@pytest.fixture(autouse=True)
def clean_bus():
    tele_events.get_bus().reset()
    yield tele_events.get_bus()
    tele_events.get_bus().reset()


# --- unit: the shrink arithmetic and backoff policy --------------------------


def test_shrink_gang_size_math():
    # largest P' < P whose device count divides K, one device per worker
    assert elastic.shrink_gang_size(8, 4) == 2  # 3 does not divide 8
    assert elastic.shrink_gang_size(8, 2) == 1
    assert elastic.shrink_gang_size(6, 4) == 3
    assert elastic.shrink_gang_size(5, 2) == 1  # K % 1 == 0 always
    assert elastic.shrink_gang_size(4, 1) is None  # nothing below 1
    # multi-device workers can genuinely strand a K
    assert elastic.shrink_gang_size(6, 2, devices_per_worker=4) is None
    assert elastic.shrink_gang_size(8, 2, devices_per_worker=4) == 1
    assert elastic.shrink_gang_size(16, 4, devices_per_worker=4) == 2


def test_backoff_growth_cap_and_determinism():
    import random

    # jitter 0: pure capped doubling
    rng = random.Random(0)
    seq = [elastic.backoff_seconds(s, 1.0, 8.0, 0.0, rng)
           for s in range(1, 7)]
    assert seq == [1.0, 2.0, 4.0, 8.0, 8.0, 8.0]
    # base <= 0 disables; streak 0 never waits
    assert elastic.backoff_seconds(3, 0.0, 8.0, 0.5, rng) == 0.0
    assert elastic.backoff_seconds(0, 1.0, 8.0, 0.5, rng) == 0.0
    # jittered values stay inside [1-j, 1+j] x the capped delay, and the
    # seeded stream is reproducible (deterministic chaos runs)
    a = [elastic.backoff_seconds(s, 0.5, 4.0, 0.5, random.Random(7))
         for s in range(1, 5)]
    b = [elastic.backoff_seconds(s, 0.5, 4.0, 0.5, random.Random(7))
         for s in range(1, 5)]
    assert a == b
    for s, v in enumerate(a, start=1):
        d = min(4.0, 0.5 * 2 ** (s - 1))
        assert 0.5 * d <= v <= 1.5 * d


class _DeadProc:
    """A worker that is already dead with exit code 3."""

    def __init__(self, spawned):
        spawned.append(self)

    def poll(self):
        return 3

    def send_signal(self, sig):
        pass

    def wait(self, timeout=None):
        return 3


def _dead_spawner(sizes):
    spawned = []

    def spawn(worker_argv, i, n, port, python, module, quiet_tail, resume):
        if i == 0:
            sizes.append(n)
        return _DeadProc(spawned)
    return spawn


def test_supervise_shrinks_after_budget(monkeypatch):
    """--elastic=N default: same-size restarts until max_restarts
    consecutive failures, then reform at P' instead of giving up; give up
    only when even the 1-worker gang burns its budget."""
    sizes = []
    restarts = []
    monkeypatch.setattr(elastic, "_spawn", _dead_spawner(sizes))
    rc = elastic.supervise(
        [], 4, max_restarts=1, poll_s=0.0, resume=False,
        num_splits=8, shrink="auto", backoff_base_s=0.0,
        on_restart=lambda gen, reason, old, new, backoff:
            restarts.append((old, new)),
    )
    assert rc == 3
    # 4,4 (budget burns) -> 2,2 (8 % 3 != 0, so 4 shrinks to 2) -> 1,1
    assert sizes == [4, 4, 2, 2, 1, 1]
    assert (4, 2) in restarts and (2, 1) in restarts


def test_supervise_shrinks_immediately(monkeypatch):
    """shrink="now" (--elastic=shrink): the first loss at each size
    reforms the gang — no same-size retries on the way down."""
    sizes = []
    monkeypatch.setattr(elastic, "_spawn", _dead_spawner(sizes))
    rc = elastic.supervise(
        [], 4, max_restarts=1, poll_s=0.0, resume=False,
        num_splits=8, shrink="now", backoff_base_s=0.0,
    )
    assert rc == 3
    assert sizes == [4, 2, 1, 1]  # 1-worker gang still gets its budget


def test_supervise_shrink_now_spares_stalled_gang(monkeypatch):
    """A STALL has every process alive (transient wedge), so shrink="now"
    must not downsize on it: stalls burn the restart budget like before,
    and shrink fires only when the budget exhausts."""
    sizes = []

    class Wedged:
        def poll(self):
            return None

        def send_signal(self, sig):
            pass

        def wait(self, timeout=None):
            return -9

    def spawn(worker_argv, i, n, port, python, module, quiet_tail, resume):
        if i == 0:
            sizes.append(n)
        return Wedged()

    monkeypatch.setattr(elastic, "_spawn", spawn)
    rc = elastic.supervise(
        [], 2, max_restarts=1, poll_s=0.0, resume=False,
        num_splits=4, shrink="now", backoff_base_s=0.0,
        progress_token=lambda: 42, stall_timeout_s=0.01,
    )
    assert rc == 1
    # first stall: same-size restart (no immediate shrink); second stall
    # exhausts the budget -> shrink to 1; then the 1-gang burns its own
    assert sizes == [2, 2, 1, 1]


def test_supervise_shrink_rejects_non_divisor(monkeypatch, capsys):
    """No smaller gang's devices divide K -> loud give-up, not a crash
    loop (4-chip workers, K=6: 1 worker = 4 devices, 6 % 4 != 0)."""
    sizes = []
    monkeypatch.setattr(elastic, "_spawn", _dead_spawner(sizes))
    rc = elastic.supervise(
        [], 2, max_restarts=0, poll_s=0.0, resume=False,
        num_splits=6, shrink="now", devices_per_worker=4,
        backoff_base_s=0.0,
    )
    assert rc == 3
    assert sizes == [2]  # never relaunched
    err = capsys.readouterr().err
    assert "cannot reform the gang" in err and "numSplits=6" in err


def test_supervise_shrink_strips_explicit_mesh(monkeypatch):
    """A user --mesh pins the OLD device grid; the reformed gang drops it
    and re-infers from P' (same-size generations keep it)."""
    lines = []

    def spawn(worker_argv, i, n, port, python, module, quiet_tail, resume):
        lines.append((n, list(worker_argv)))
        return _DeadProc([])

    monkeypatch.setattr(elastic, "_spawn", spawn)
    elastic.supervise(
        ["--mesh=4", "--lambda=.01"], 4, max_restarts=0, poll_s=0.0,
        resume=False, num_splits=8, shrink="now", backoff_base_s=0.0,
    )
    by_size = {n: argv for n, argv in lines}
    assert "--mesh=4" in by_size[4]
    assert "--mesh=4" not in by_size[2] and "--lambda=.01" in by_size[2]


def test_supervise_emits_gang_resize_and_schema_valid(monkeypatch,
                                                      tmp_path):
    """The typed gang_resize / restart events land in the JSONL and pass
    the schema checker like every other dialect."""
    ev = tmp_path / "events.jsonl"
    tele_events.get_bus().configure(jsonl_path=str(ev))
    monkeypatch.setattr(elastic, "_spawn", _dead_spawner([]))
    elastic.supervise(
        [], 4, max_restarts=0, poll_s=0.0, resume=False,
        num_splits=8, shrink="auto", backoff_base_s=0.0,
    )
    assert tele_schema.check_file(str(ev)) == []
    recs = [json.loads(ln) for ln in ev.read_text().splitlines()]
    resizes = [r for r in recs if r["event"] == "gang_resize"]
    assert [(r["old_size"], r["new_size"]) for r in resizes] == [(4, 2),
                                                                 (2, 1)]
    restarts = [r for r in recs if r["event"] == "restart"]
    assert restarts and all("gang_size" in r and "backoff_s" in r
                            for r in restarts)
    # a resize must still report the attempts that exhausted the budget,
    # never "attempt 0" (the counter resets AFTER the event)
    assert all(r["attempt"] >= 1 for r in restarts)


def test_supervise_dumps_victim_flightrec(monkeypatch, tmp_path):
    """A worker death with --events configured leaves a `.flightrec`
    explanation artifact: the supervisor tails the victim's stream
    (telemetry/recorder.dump_victim) before deciding the restart.  Dead
    worker 0 here, so the victim stream is the shared events file — the
    pre-seeded worker events must be what the dump carries."""
    ev = tmp_path / "events.jsonl"
    with open(ev, "w") as f:
        for t in (5, 10):
            f.write(json.dumps(
                {"event": "checkpoint_write", "seq": t, "pid": 777,
                 "ts": float(t), "algorithm": "ToyGang", "round": t,
                 "path": "x"}) + "\n")
    tele_events.get_bus().configure(jsonl_path=str(ev))
    monkeypatch.setattr(elastic, "_spawn", _dead_spawner([]))
    elastic.supervise([], 2, max_restarts=0, poll_s=0.0, resume=False,
                      num_splits=4, shrink="now", backoff_base_s=0.0)
    path = str(ev) + ".flightrec"
    assert os.path.exists(path)
    assert tele_schema.check_file(path) == []
    recs = [json.loads(ln) for ln in open(path)]
    man = recs[0]["flightrec_manifest"]
    assert man["reason"] == "worker_died" and man["source"] == "supervisor"
    assert man["victim_index"] == 0 and man["exit_code"] == 3
    # _DeadProc has no pid to scope by — the dump is the stream's
    # last-known state, and says so
    assert man["scope"] == "stream"
    assert any(r.get("event") == "checkpoint_write" and r["pid"] == 777
               for r in recs[1:])


def test_metrics_writer_gang_gauges(tmp_path):
    """gang_resize / restart / checkpoint_corrupt events drive the new
    gauges and counters; the gang families render as a dedicated subset
    so the supervisor's sibling `<metrics>.gang` file never duplicates
    worker series (textfile collectors reject duplicate families)."""
    from cocoa_tpu.telemetry.metrics import MetricsWriter

    path = tmp_path / "m.prom"
    w = MetricsWriter(str(path))
    # a worker that never sees gang events must not render gang families
    assert "cocoa_gang" not in path.read_text()
    base = {"seq": 1, "ts": 0.0, "pid": 1}
    w({**base, "event": "restart", "reason": "worker_died", "attempt": 1,
       "generation": 1, "gang_size": 4, "backoff_s": 1.5})
    w({**base, "event": "gang_resize", "reason": "worker_died",
       "old_size": 4, "new_size": 2, "generation": 2})
    w({**base, "event": "checkpoint_corrupt", "algorithm": "CoCoA+",
       "path": "x.npz", "reason": "torn"})
    text = path.read_text()
    assert "cocoa_gang_size 2" in text
    assert "cocoa_gang_generations_total 3" in text
    assert "cocoa_restart_backoff_seconds 1.5" in text
    assert "cocoa_checkpoint_corrupt_total 1" in text

    # the supervisor's gang-only writer: gang families and NOTHING else
    gpath = tmp_path / "m.prom.gang"
    g = MetricsWriter(str(gpath), families="gang")
    g({**base, "event": "gang_resize", "reason": "worker_died",
       "old_size": 2, "new_size": 1, "generation": 1})
    gtext = gpath.read_text()
    assert "cocoa_gang_size 1" in gtext
    assert "cocoa_gang_generations_total 2" in gtext
    assert "cocoa_rounds_total" not in gtext
    assert "cocoa_restarts_total" not in gtext
    with pytest.raises(ValueError, match="families"):
        MetricsWriter(str(gpath), families="nope")


# --- CLI flag surface --------------------------------------------------------


def _cli_spy(monkeypatch):
    calls = {}

    def spy(worker_argv, n_workers, **kw):
        calls["argv"] = worker_argv
        calls["n"] = n_workers
        calls.update(kw)
        return 0

    monkeypatch.setattr("cocoa_tpu.elastic.supervise", spy)
    return calls


BASE_FLAGS = ["--trainFile=x.dat", "--numFeatures=10", "--numSplits=4"]


def test_cli_elastic_shrink_specs(monkeypatch):
    from cocoa_tpu import cli

    calls = _cli_spy(monkeypatch)
    assert cli.main(BASE_FLAGS + ["--elastic=2"]) == 0
    assert calls["n"] == 2 and calls["shrink"] == "auto"
    assert calls["num_splits"] == 4

    calls = _cli_spy(monkeypatch)
    assert cli.main(BASE_FLAGS + ["--elastic=2,shrink"]) == 0
    assert calls["n"] == 2 and calls["shrink"] == "now"

    calls = _cli_spy(monkeypatch)
    assert cli.main(BASE_FLAGS + ["--elastic=shrink",
                                  "--numProcesses=3"]) == 0
    assert calls["n"] == 3 and calls["shrink"] == "now"

    # multi-chip workers declare their device count so shrink sizes
    # against DEVICES, not processes (it can never be probed — the
    # supervisor must not initialize a backend its workers need)
    calls = _cli_spy(monkeypatch)
    assert cli.main(BASE_FLAGS + ["--elastic=2,shrink,devices=4"]) == 0
    assert calls["n"] == 2 and calls["shrink"] == "now"
    assert calls["devices_per_worker"] == 4


def test_cli_elastic_shrink_rejections(monkeypatch, capsys):
    from cocoa_tpu import cli

    _cli_spy(monkeypatch)
    # bare shrink with no gang size anywhere
    assert cli.main(BASE_FLAGS + ["--elastic=shrink"]) == 2
    assert "gang size" in capsys.readouterr().err
    # junk spec
    assert cli.main(BASE_FLAGS + ["--elastic=two"]) == 2
    capsys.readouterr()
    # devices= must be a positive integer
    assert cli.main(BASE_FLAGS + ["--elastic=2,devices=0"]) == 2
    assert cli.main(BASE_FLAGS + ["--elastic=2,devices=x"]) == 2
    capsys.readouterr()
    # fp gang cannot shrink: explicit ask rejected loudly...
    assert cli.main(BASE_FLAGS + ["--elastic=2,shrink", "--fp=2"]) == 2
    assert "feature-parallel" in capsys.readouterr().err
    # ...the default degrades to same-size supervision with a note
    calls = _cli_spy(monkeypatch)
    assert cli.main(BASE_FLAGS + ["--elastic=2", "--fp=2"]) == 0
    assert calls["shrink"] == "off"
    assert "same-size restarts" in capsys.readouterr().err


# --- checkpoint generations + validation ------------------------------------


def _save_rounds(directory, rounds, alg="CoCoA+", d=8, k=2, n=4):
    rng = np.random.default_rng(0)
    for t in rounds:
        ckpt_lib.save(str(directory), alg, t,
                      jnp.asarray(rng.random(d)),
                      jnp.asarray(rng.random((k, n))), seed=0)


def test_checkpoint_keeps_two_generations(tmp_path):
    _save_rounds(tmp_path, [5, 10, 15, 20])
    paths = ckpt_lib.generations(str(tmp_path), "CoCoA+")
    assert [os.path.basename(p) for p in paths] == [
        "CoCoA+-r000015.npz", "CoCoA+-r000020.npz"]
    # sidecars pruned with their archives
    assert sorted(f for f in os.listdir(tmp_path) if f.endswith(".json")) \
        == ["CoCoA+-r000015.npz.json", "CoCoA+-r000020.npz.json"]
    # per-algorithm: another algorithm's files are never claimed
    _save_rounds(tmp_path, [5], alg="CoCoA")
    assert len(ckpt_lib.generations(str(tmp_path), "CoCoA+")) == 2
    assert len(ckpt_lib.generations(str(tmp_path), "CoCoA")) == 1


def test_checkpoint_generations_order_numerically(tmp_path):
    """Past round 999999 the 06d stamp widens: ordering must follow the
    ROUND, not the string, or pruning would delete the newest file."""
    _save_rounds(tmp_path, [999998, 999999, 1000000])
    paths = ckpt_lib.generations(str(tmp_path), "CoCoA+")
    assert [os.path.basename(p) for p in paths] == [
        "CoCoA+-r999999.npz", "CoCoA+-r1000000.npz"]
    assert ckpt_lib.latest(str(tmp_path), "CoCoA+").endswith(
        "CoCoA+-r1000000.npz")


def test_checkpoint_prune_spares_stale_higher_rounds(tmp_path):
    """A reused directory holding HIGHER-round leftovers from an earlier
    run must not make pruning eat the fresh run's own saves."""
    _save_rounds(tmp_path, [400, 500])   # the earlier run's leftovers
    _save_rounds(tmp_path, [100])        # a fresh run starts over
    names = [os.path.basename(p)
             for p in ckpt_lib.generations(str(tmp_path), "CoCoA+")]
    # the just-written r100 survives; the stale files stay untouched
    # (exactly as benign/visible as before pruning existed)
    assert names == ["CoCoA+-r000100.npz", "CoCoA+-r000400.npz",
                     "CoCoA+-r000500.npz"]


def test_checkpoint_validate_rejects_bare_npy(tmp_path):
    """A stray .npy overwriting the checkpoint makes np.load return a
    plain ndarray — validate must report it (and latest fall back), not
    crash closing a handle that has no close()."""
    _save_rounds(tmp_path, [5, 10])
    prev, newest = ckpt_lib.generations(str(tmp_path), "CoCoA+")
    np.save(open(newest, "wb"), np.zeros(3))
    assert ckpt_lib.validate(newest) == "not an npz archive"
    assert ckpt_lib.latest(str(tmp_path), "CoCoA+") == prev


def test_checkpoint_validate_catches_corruption(tmp_path):
    _save_rounds(tmp_path, [5, 10])
    good, newest = ckpt_lib.generations(str(tmp_path), "CoCoA+")
    assert ckpt_lib.validate(newest) is None
    # torn file (half-written copy)
    with open(newest, "r+b") as f:
        f.truncate(100)
    assert "unreadable" in (ckpt_lib.validate(newest) or "")
    # garbage overwrite: zip opens nothing
    with open(newest, "wb") as f:
        f.write(b"\x00" * 4096)
    assert ckpt_lib.validate(newest) is not None
    assert ckpt_lib.validate(good) is None


def test_checkpoint_validate_catches_shape_mismatch(tmp_path):
    _save_rounds(tmp_path, [5])
    (path,) = ckpt_lib.generations(str(tmp_path), "CoCoA+")
    meta, arrays = ckpt_lib.load_full(path)
    # rewrite the archive with a truncated w but the original meta: the
    # recorded shapes disagree -> rejected
    arrays["w"] = arrays["w"][:-2]
    np.savez(open(path, "wb"), _meta=np.array(json.dumps(meta)), **arrays)
    reason = ckpt_lib.validate(path)
    assert reason is not None and "shape" in reason


def test_latest_falls_back_to_previous_generation(tmp_path, clean_bus):
    seen = []
    clean_bus.subscribe(seen.append)
    _save_rounds(tmp_path, [5, 10])
    prev, newest = ckpt_lib.generations(str(tmp_path), "CoCoA+")
    with open(newest, "r+b") as f:
        f.truncate(100)
    assert ckpt_lib.latest(str(tmp_path), "CoCoA+") == prev
    corrupt = [r for r in seen if r["event"] == "checkpoint_corrupt"]
    assert len(corrupt) == 1 and corrupt[0]["path"] == newest
    # both generations torn -> None (and the caller starts from round 1,
    # which is correct, not a crash)
    with open(prev, "r+b") as f:
        f.truncate(100)
    assert ckpt_lib.latest(str(tmp_path), "CoCoA+") is None


def test_corrupt_newest_resumes_previous_bit_identical(tmp_path):
    """End to end on the real solver: tear the newest checkpoint; the
    resume falls back one generation and REPLAYS to the same final state
    bit for bit (round-keyed sampling makes the extra rounds free)."""
    from cocoa_tpu.config import DebugParams, Params
    from cocoa_tpu.data.sharding import shard_dataset
    from cocoa_tpu.data.synth import synth_sparse
    from cocoa_tpu.solvers import run_cocoa

    data = synth_sparse(64, 32, nnz_mean=6, seed=4)
    ds = shard_dataset(data, k=2, layout="dense", dtype=jnp.float64)
    p = Params(n=data.n, num_rounds=20, local_iters=8, lam=0.01)
    d = DebugParams(debug_iter=5, seed=0, chkpt_iter=5,
                    chkpt_dir=str(tmp_path))
    w_full, a_full, _ = run_cocoa(ds, p, d, plus=True, quiet=True)
    gens = ckpt_lib.generations(str(tmp_path), "CoCoA+")
    assert [os.path.basename(g) for g in gens] == [
        "CoCoA+-r000015.npz", "CoCoA+-r000020.npz"]
    with open(gens[-1], "r+b") as f:
        f.truncate(80)
    path = ckpt_lib.latest(str(tmp_path), "CoCoA+")
    assert path == gens[0]
    meta, w0, a0 = ckpt_lib.load(path)
    assert meta["round"] == 15
    w_res, a_res, _ = run_cocoa(
        ds, p, DebugParams(debug_iter=5, seed=0), plus=True, quiet=True,
        w_init=w0, alpha_init=a0, start_round=16)
    np.testing.assert_array_equal(np.asarray(w_res), np.asarray(w_full))
    np.testing.assert_array_equal(np.asarray(a_res), np.asarray(a_full))


# --- bounded KV ops ----------------------------------------------------------


class _NeverClient:
    """blocking_key_value_get that always times out (dead peer)."""

    def __init__(self):
        self.calls = 0

    def key_value_set(self, key, val):
        pass

    def blocking_key_value_get(self, key, timeout_ms):
        self.calls += 1
        time.sleep(timeout_ms / 1000.0)
        raise RuntimeError("DEADLINE_EXCEEDED: Deadline Exceeded")


class _FlakyClient:
    """Fails fast twice (transient coordinator error), then succeeds."""

    def __init__(self):
        self.calls = 0

    def blocking_key_value_get(self, key, timeout_ms):
        self.calls += 1
        if self.calls < 3:
            raise RuntimeError("UNAVAILABLE: connection reset")
        return "ok"


def test_blocking_kv_get_bounded_and_actionable(monkeypatch):
    monkeypatch.setattr(distributed, "_KV_BACKOFF_BASE_S", 0.001)
    client = _NeverClient()
    t0 = time.monotonic()
    with pytest.raises(RuntimeError) as e:
        distributed.blocking_kv_get(client, "cocoa/x/1/n",
                                    timeout_s=0.3, attempt_s=0.05,
                                    what="peer process 1, exchange 'x'")
    elapsed = time.monotonic() - t0
    assert elapsed < 5.0                      # bounded, not 600 s
    assert client.calls >= 2                  # it retried
    msg = str(e.value)
    assert "cocoa/x/1/n" in msg and "peer process 1" in msg
    assert "--elastic" in msg                 # names the remedy


def test_blocking_kv_get_no_backoff_after_slow_attempts():
    """An attempt that consumed its blocking wait was LISTENING the whole
    time — no backoff sleep after it, or the budget is spent deaf.  With
    0.3s budget / 0.05s attempts the client must be polled many times."""
    client = _NeverClient()
    with pytest.raises(RuntimeError):
        distributed.blocking_kv_get(client, "k", timeout_s=0.3,
                                    attempt_s=0.05)
    assert client.calls >= 4


def test_blocking_kv_get_retries_transient_errors(monkeypatch):
    # backoff pauses shrunk so the test is instant
    monkeypatch.setattr(distributed, "_KV_BACKOFF_BASE_S", 0.001)
    client = _FlakyClient()
    assert distributed.blocking_kv_get(client, "k", timeout_s=5.0,
                                       attempt_s=0.1) == "ok"
    assert client.calls == 3


def test_host_allgather_names_missing_peer(monkeypatch):
    client = _NeverClient()
    monkeypatch.setattr(distributed, "kv_client", lambda: client)
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(jax, "process_index", lambda: 0)
    with pytest.raises(RuntimeError, match="peer process 1"):
        distributed.host_allgather_bytes("tag0", b"payload",
                                         timeout_s=0.2, attempt_s=0.05)


# --- real-process gang: kill -> shrink -> bit-identical ----------------------


def _gang_env(monkeypatch):
    # workers must see the repo + tests on PYTHONPATH and must not
    # inherit the virtual 8-device flag (they use no devices, but keep
    # the environment identical to the real gang tests)
    monkeypatch.setenv(
        "PYTHONPATH",
        f"{ROOT}{os.pathsep}{TESTS}{os.pathsep}"
        f"{os.environ.get('PYTHONPATH', '')}")
    monkeypatch.setenv("XLA_FLAGS", " ".join(
        f for f in os.environ.get("XLA_FLAGS", "").split()
        if "host_platform_device_count" not in f))


def _toy_argv(ckdir, k=4, rounds=20, step_s=0.05):
    return [f"--chkptDir={ckdir}", f"--numSplits={k}",
            f"--numRounds={rounds}", "--chkptIter=5",
            f"--stepSeconds={step_s}"]


def _run_toy_control(ckdir, k=4, rounds=20, step_s=0.05):
    rc = elastic.supervise(_toy_argv(ckdir, k, rounds, step_s), 2,
                           module="_gang_worker", max_restarts=0,
                           poll_s=0.05, backoff_base_s=0.0)
    assert rc == 0
    return ckpt_lib.load(ckpt_lib.latest(str(ckdir), "ToyGang"))


def _tear_once_on_restart(ckdir):
    """on_restart hook: tear the newest checkpoint exactly once, AFTER
    the gang is down and BEFORE the survivors relaunch — the
    deterministic window where no writer can replace the torn file."""
    done = []

    def hook(gen, reason, old, new, backoff):
        if not done:
            truncate_newest_checkpoint(ckdir)([])
            done.append(gen)
    return hook


@pytest.mark.slow
def test_gang_sigkill_shrinks_to_survivor_bit_identical(tmp_path,
                                                        monkeypatch):
    """A REAL 2-process jax.distributed gang (the toy worker: real
    rendezvous, real KV allgather per round, real checkpoints) loses
    worker 1 to SIGKILL mid-run; the supervisor reforms at P'=1, the
    survivor resumes and completes — final state bit-identical to the
    unfailed 2-process control.  With --events on the workers, the
    SIGKILL additionally yields a validated `.flightrec` dump from the
    supervisor path carrying the victim's last-N events (the ISSUE-10
    acceptance pin)."""
    _gang_env(monkeypatch)
    ck = tmp_path / "ck"
    ev = tmp_path / "events.jsonl"
    tele_events.get_bus().configure(jsonl_path=str(ev))
    plan = FaultPlan(
        Fault(generation=0, actions=(sigkill(1),),
              trigger=checkpoint_at_least(ck, "ToyGang", 5),
              name="kill-worker-1"),
    )
    resizes = []
    # --trace as well: spans flow from round 1, so the victim's stream
    # is deterministically nonempty whenever the kill lands (checkpoint
    # events alone would race — the trigger can fire on worker 0's save
    # before worker 1 has written anything)
    rc = elastic.supervise(
        _toy_argv(ck) + [f"--events={ev}", "--trace"], 2,
        module="_gang_worker",
        max_restarts=3,
        poll_s=0.05, num_splits=4, shrink="now", backoff_base_s=0.0,
        on_generation=plan.on_generation,
        on_restart=lambda gen, reason, old, new, backoff:
            resizes.append((old, new)),
    )
    plan.join()
    assert rc == 0
    assert plan.errors == []
    assert plan.fired == ["kill-worker-1"]
    assert (2, 1) in resizes
    meta, w, _ = ckpt_lib.load(ckpt_lib.latest(str(ck), "ToyGang"))
    assert meta["round"] == 20

    # unfailed 2-process control: bit-identical final state
    meta_c, w_c, _ = _run_toy_control(tmp_path / "ref")
    assert meta_c["round"] == 20
    np.testing.assert_array_equal(w, w_c)

    # the machine-readable trace validates like every other dialect and
    # records the resize
    assert tele_schema.check_file(str(ev)) == []
    recs = [json.loads(ln) for ln in ev.read_text().splitlines()]
    assert any(r["event"] == "gang_resize" and r["new_size"] == 1
               for r in recs)

    # the crash explanation artifact: the SIGKILLed worker 1 could not
    # dump its own ring, so the supervisor tailed worker 1's stream
    # (`<events>.p1`) and dumped on its behalf — a validated flightrec
    # naming the victim and carrying its last events (the checkpoint
    # writes that were its final observable acts)
    frec = str(ev) + ".p1.flightrec"
    assert os.path.exists(frec)
    assert tele_schema.check_file(frec) == []
    frecs = [json.loads(ln) for ln in open(frec)]
    man = frecs[0]["flightrec_manifest"]
    assert man["reason"] == "worker_died"
    assert man["source"] == "supervisor" and man["victim_index"] == 1
    # a real Popen victim: the tail is scoped to the dead process's pid
    assert man["scope"] == "victim"
    victim_events = frecs[1:]
    assert victim_events, "the dump must carry the victim's events"
    assert {r["pid"] for r in victim_events} == {man["victim_pid"]}
    # worker 1 was mid-flight: its last observable acts — round spans
    # (guaranteed from round 1) and usually its round-5 checkpoint
    assert any(r["event"] == "span" for r in victim_events)


@pytest.mark.slow
def test_gang_kill_plus_torn_checkpoint_resumes_previous(tmp_path,
                                                         monkeypatch,
                                                         capfd):
    """Same loss, but the newest checkpoint is ALSO torn (the half-copied
    file a preemption leaves — injected in the on_restart window, after
    teardown and before relaunch, so no writer can race it): the survivor
    falls back one generation, replays the extra rounds, and still lands
    bit-identical to the control."""
    _gang_env(monkeypatch)
    ck = tmp_path / "ck"
    # slower rounds: the kill lands while r10 is still the newest save,
    # so the torn newest is r10 and the fallback generation is r5
    plan = FaultPlan(
        Fault(generation=0, actions=(sigkill(1),),
              trigger=checkpoint_at_least(ck, "ToyGang", 10),
              name="kill-worker-1"),
    )
    rc = elastic.supervise(
        _toy_argv(ck, step_s=0.15), 2, module="_gang_worker",
        max_restarts=3, poll_s=0.05, num_splits=4, shrink="now",
        backoff_base_s=0.0, on_generation=plan.on_generation,
        on_restart=_tear_once_on_restart(ck),
    )
    plan.join()
    assert rc == 0
    assert plan.errors == []
    assert plan.fired == ["kill-worker-1"]
    meta, w, _ = ckpt_lib.load(ckpt_lib.latest(str(ck), "ToyGang"))
    assert meta["round"] == 20
    # the survivor resumed from the PREVIOUS generation (round 5, not the
    # torn round-10 file) — worker 0 inherits stdout, so its resume line
    # is observable here
    out = capfd.readouterr().out
    assert "resuming ToyGang from round 5" in out
    meta_c, w_c, _ = _run_toy_control(tmp_path / "ref")
    np.testing.assert_array_equal(w, w_c)


# --- the real-training chaos pin (needs multi-process CPU collectives) -------


def _real_training_argv(train, ckdir, ev, rounds=200, cache_dir=None):
    argv = [
        f"--trainFile={train}", "--numFeatures=64",
        f"--numRounds={rounds}", "--localIterFrac=0.2", "--numSplits=2",
        "--lambda=.01", "--justCoCoA=true", "--debugIter=10",
        f"--chkptDir={ckdir}", "--chkptIter=10", "--dtype=float64",
        f"--events={ev}",
    ]
    if cache_dir is not None:
        argv.append(f"--ingestCache={cache_dir}")
    return argv


def _final_gaps(ev_path):
    """Last run_end gap per algorithm from an events JSONL."""
    gaps = {}
    with open(ev_path) as f:
        for line in f:
            r = json.loads(line)
            if r.get("event") == "run_end" and r.get("gap") is not None:
                gaps[r["algorithm"]] = r["gap"]
    return gaps


@pytest.mark.slow
@pytest.mark.parametrize("tear_newest", [False, True],
                         ids=["sigkill", "sigkill+torn-ckpt"])
def test_chaos_real_training_shrink_bit_identical(tmp_path, monkeypatch,
                                                  tear_newest):
    """THE chaos pin: a real 2-process localhost training gang with one
    worker SIGKILLed mid-run completes on the survivor (P'=1) and its
    final (w, alpha, gap) is bit-identical to the unfailed 2-process
    control; with the newest checkpoint also torn, the survivor resumes
    from the previous generation and the pin still holds.  The chaos arm
    rides --ingestCache (the control stays uncached — slab-cache
    bit-identity is part of what the A/B proves): the shrunken
    generation's re-ingest must be a full cache hit with ZERO re-parsed
    bytes (the ISSUE-15 shrink contract — shard artifacts are
    geometry-free, so the survivor maps its inherited shards warm)."""
    if not hasattr(jax, "shard_map"):
        pytest.skip("the 2-process training gang rides the mesh path, "
                    "which needs jax.shard_map (newer jax)")
    from cocoa_tpu.data.synth import synth_sparse, write_libsvm

    _gang_env(monkeypatch)
    data = synth_sparse(96, 64, nnz_mean=8, seed=2)
    train = tmp_path / "train.dat"
    write_libsvm(data, str(train))

    ck = tmp_path / "ck"
    ev = tmp_path / "events.jsonl"
    tele_events.get_bus().configure(jsonl_path=str(ev))
    plan = FaultPlan(
        Fault(generation=0, actions=(sigkill(1),),
              trigger=checkpoint_at_least(ck, "CoCoA+", 10),
              name="chaos"),
    )
    rc = elastic.supervise(
        _real_training_argv(train, ck, ev,
                            cache_dir=tmp_path / "icache"),
        2, max_restarts=3,
        num_splits=2, shrink="now", backoff_base_s=0.2,
        on_generation=plan.on_generation,
        # tearing in the on_restart window (gang down, survivors not yet
        # relaunched) is the only race-free injection point — a live
        # worker 0 could otherwise land a fresh save after the tear
        on_restart=(_tear_once_on_restart(ck) if tear_newest else None),
    )
    plan.join()
    assert rc == 0
    assert plan.errors == []
    assert plan.fired == ["chaos"]

    ck_ref = tmp_path / "ck_ref"
    ev_ref = tmp_path / "events_ref.jsonl"
    rc_ref = elastic.supervise(
        _real_training_argv(train, ck_ref, ev_ref), 2, max_restarts=0,
    )
    assert rc_ref == 0

    for alg in ("CoCoA+", "CoCoA"):
        path = ckpt_lib.latest(str(ck), alg)
        path_ref = ckpt_lib.latest(str(ck_ref), alg)
        assert path is not None and path_ref is not None
        meta, w, a = ckpt_lib.load(path)
        meta_r, w_r, a_r = ckpt_lib.load(path_ref)
        assert meta["round"] == meta_r["round"] == 200
        np.testing.assert_array_equal(w, w_r)
        np.testing.assert_array_equal(a, a_r)
    # the certified gap agrees exactly too (run_end carries it)
    assert _final_gaps(ev) == _final_gaps(ev_ref)
    assert tele_schema.check_file(str(ev)) == []
    recs = [json.loads(ln) for ln in ev.read_text().splitlines()]
    if tear_newest:
        assert any(r["event"] == "checkpoint_corrupt" for r in recs)
    # the shrink re-ingest contract: the reformed generation (the last
    # ingest on worker 0's stream, after the gang_resize) served every
    # inherited shard from the slab cache — zero re-parsed bytes
    ingests = [r for r in recs if r["event"] == "ingest"]
    assert ingests and ingests[0]["cache"] == "miss"
    assert ingests[-1]["cache"] == "hit"
    assert ingests[-1]["bytes_read"] == 0
