"""Literal NumPy oracle of the reference update rules.

Transcribes the *math* of the Scala reference (with file:line citations) as
plainly as possible — deliberately unvectorized and slow, so the production
JAX kernels have an independent ground truth to match bit-closely in x64.

All functions operate on dense numpy rows; padding/masking concerns of the
device layouts do not exist here.
"""

from __future__ import annotations

import numpy as np


def local_sdca(
    X: np.ndarray,          # (n_local, d) dense rows of this shard
    y: np.ndarray,          # (n_local,) labels in {-1, +1}
    w_init: np.ndarray,     # (d,) shared primal vector
    alpha: np.ndarray,      # (n_local,) local dual variables (copied, not mutated)
    idxs: np.ndarray,       # (H,) sampled coordinates for this round
    lam: float,
    n: int,                 # GLOBAL example count (primal-dual correspondence)
    plus: bool,
    sigma: float,           # sigma' = K * gamma (CoCoA.scala:45)
):
    """Reference localSDCA (CoCoA.scala:130-192). Returns (delta_alpha, delta_w)."""
    w = w_init.copy()
    alpha = alpha.copy()
    alpha_old = alpha.copy()
    delta_w = np.zeros_like(w_init)
    lam_n = lam * n

    for idx in idxs:
        x = X[idx]
        yy = y[idx]
        # hinge-loss gradient (CoCoA.scala:157-163)
        if plus:
            grad = (yy * (x @ w + sigma * (x @ delta_w)) - 1.0) * lam_n
        else:
            grad = (yy * (x @ w) - 1.0) * lam_n
        # projection onto the box-constraint active set (CoCoA.scala:166-170)
        proj_grad = grad
        if alpha[idx] <= 0.0:
            proj_grad = min(grad, 0.0)
        elif alpha[idx] >= 1.0:
            proj_grad = max(grad, 0.0)
        if abs(proj_grad) != 0.0:
            xnorm2 = float(x @ x)
            qii = xnorm2 * sigma if plus else xnorm2  # CoCoA.scala:173-174
            new_alpha = 1.0
            if qii != 0.0:
                new_alpha = min(max(alpha[idx] - grad / qii, 0.0), 1.0)
            update = x * (yy * (new_alpha - alpha[idx]) / lam_n)  # :181
            if not plus:
                w = w + update               # local view advances (:182-184)
            delta_w = delta_w + update       # :185
            alpha[idx] = new_alpha           # :186
    return alpha - alpha_old, delta_w


def minibatch_cd_partition(
    X, y, w_init, alpha, idxs, lam, n, scaling
):
    """Reference MinibatchCD.partitionUpdate (MinibatchCD.scala:76-132).

    Like localSDCA but the gradient always reads the frozen w (:104) and the
    local w never advances; alpha *does* advance within the batch (:123).
    Returns (delta_w, alpha_scaled) where alpha_scaled = alpha_old +
    scaling * delta_alpha (:127-128).
    """
    alpha = alpha.copy()
    alpha_old = alpha.copy()
    delta_w = np.zeros_like(w_init)
    lam_n = lam * n
    for idx in idxs:
        x = X[idx]
        yy = y[idx]
        grad = (yy * (x @ w_init) - 1.0) * lam_n
        proj_grad = grad
        if alpha[idx] <= 0.0:
            proj_grad = min(grad, 0.0)
        elif alpha[idx] >= 1.0:
            proj_grad = max(grad, 0.0)
        if abs(proj_grad) != 0.0:
            qii = float(x @ x)
            new_alpha = 1.0
            if qii != 0.0:
                new_alpha = min(max(alpha[idx] - grad / qii, 0.0), 1.0)
            delta_w = delta_w + x * (yy * (new_alpha - alpha[idx]) / lam_n)
            alpha[idx] = new_alpha
    return delta_w, alpha_old + scaling * (alpha - alpha_old)


def sgd_partition(X, y, w_init, idxs, lam, t_global, local):
    """Reference SGD.partitionUpdate (SGD.scala:87-139).

    local=True: Pegasos-style steps on a private w copy, eta = 1/(lam*(t+i)),
    returns w - w_init (:117-134).  local=False: sum of raw hinge
    subgradients x*y over the draws (:124-127).
    """
    w = w_init.copy()
    delta_w = np.zeros_like(w_init)
    for i, idx in enumerate(idxs, start=1):
        step = 1.0 / (lam * (t_global + i))
        x = X[idx]
        yy = y[idx]
        evaluation = 1.0 - yy * (x @ w)
        if local:
            w = w * (1.0 - step * lam)
        if evaluation > 0:
            delta_w = delta_w + x * yy
            if local:
                w = w + x * (yy * step)
        if local:
            delta_w = w - w_init
    return delta_w


def dist_gd_partition(X, y, w_init, lam, include_oob_bug: bool = False):
    """Reference DistGD.partitionUpdate (DistGD.scala:67-102).

    Deterministic pass over the shard accumulating active-hinge subgradients,
    then the per-worker regularizer term -lam*w_init (:98).  The reference's
    inclusive loop bound (`0 to nLocal`, :82) reads one element past the end —
    we fix that (SURVEY.md reference bug #1); ``include_oob_bug`` exists only
    to document the deviation, not to reproduce a JVM crash.
    """
    if include_oob_bug:
        raise NotImplementedError("the out-of-bounds read is a reference bug")
    delta_w = np.zeros_like(w_init)
    for i in range(X.shape[0]):
        x = X[i]
        yy = y[i]
        if 1.0 - yy * (x @ w_init) > 0:
            delta_w = delta_w + x * yy
    return delta_w - lam * w_init


# ---- objectives (OptUtils.scala:57-98) ----

def hinge_loss(X, y, w):
    return np.maximum(1.0 - y * (X @ w), 0.0)


def primal_objective(X, y, w, lam):
    return hinge_loss(X, y, w).mean() + 0.5 * lam * float(w @ w)


def dual_objective(w, alpha_total_sum, n, lam):
    return -0.5 * lam * float(w @ w) + alpha_total_sum / n


def duality_gap(X, y, w, alpha_total_sum, lam):
    return primal_objective(X, y, w, lam) - dual_objective(
        w, alpha_total_sum, X.shape[0], lam
    )


def classification_error(X, y, w):
    return float(np.mean((X @ w) * y <= 0))


# ---- outer loops (driver-side math only) ----

def cocoa_outer(
    shards,              # list of (X_k, y_k) per shard
    w0, lam, n, num_rounds, h, beta, gamma, seed, plus,
    sample_fn,           # (seed, t, n_local) -> (H,) idx array
):
    """Reference runCoCoA (CoCoA.scala:22-66): per-round local SDCA on every
    shard, sum-reduce delta_w, w += scaling * sum, alpha_k += scaling * da_k."""
    k = len(shards)
    scaling = gamma if plus else beta / k
    sigma = k * gamma
    w = w0.copy()
    alphas = [np.zeros(Xk.shape[0]) for Xk, _ in shards]
    for t in range(1, num_rounds + 1):
        dw_sum = np.zeros_like(w)
        for s, (Xk, yk) in enumerate(shards):
            idxs = sample_fn(seed, t, Xk.shape[0])
            da, dw = local_sdca(Xk, yk, w, alphas[s], idxs, lam, n, plus, sigma)
            alphas[s] = alphas[s] + scaling * da
            dw_sum += dw
        w = w + scaling * dw_sum
    return w, alphas
