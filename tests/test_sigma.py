"""The σ′ subproblem-coupling override (--sigma, round-4 extension).

The reference hard-couples σ′ = K·γ (CoCoA.scala:45) — the paper's SAFE
aggregation bound for adversarial shard coherence.  Randomly-partitioned
data tolerates smaller σ′ (bigger effective local steps); measured on the
rcv1 benchmark config, σ′=K/2 halves the certified comm-rounds to the
1e-4 gap while anything below K/2 (already σ′=3.5 at K=8) diverges —
visibly, because the duality-gap certificate is exact for ANY (w, α).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from cocoa_tpu.config import DebugParams, Params, RunConfig
from cocoa_tpu.data import shard_dataset
from cocoa_tpu.solvers import run_cocoa
from cocoa_tpu.solvers.cocoa import _alg_config


def test_alg_config_sigma_override():
    p = Params(n=100, gamma=1.0)
    assert _alg_config(p, 4, plus=True) == ("plus", 1.0, 4.0)
    p2 = Params(n=100, gamma=1.0, sigma=2.5)
    assert _alg_config(p2, 4, plus=True) == ("plus", 1.0, 2.5)
    # non-plus CoCoA reads sigma too (its inner subproblem passes it on)
    assert _alg_config(p2, 4, plus=False)[2] == 2.5


def test_runconfig_sigma_zero_means_auto():
    cfg = RunConfig()
    assert cfg.to_params(100, 4).sigma is None
    cfg.sigma = 2.0
    assert cfg.to_params(100, 4).sigma == 2.0


def test_sigma_explicit_safe_value_matches_default(tiny_data):
    """sigma=K*gamma must reproduce the default run bit-for-bit."""
    ds = shard_dataset(tiny_data, k=4, layout="dense", dtype=jnp.float64)
    debug = DebugParams(debug_iter=5, seed=0)
    p0 = Params(n=tiny_data.n, num_rounds=10, local_iters=12, lam=1e-2)
    p1 = Params(n=tiny_data.n, num_rounds=10, local_iters=12, lam=1e-2,
                sigma=4.0)
    w0, a0, _ = run_cocoa(ds, p0, debug, plus=True, quiet=True)
    w1, a1, _ = run_cocoa(ds, p1, debug, plus=True, quiet=True)
    np.testing.assert_array_equal(np.asarray(w0), np.asarray(w1))
    np.testing.assert_array_equal(np.asarray(a0), np.asarray(a1))


def test_sigma_aggressive_converges_faster_and_certified(tiny_data):
    """On benign (randomly sharded) data a sub-K σ′ reaches a smaller gap
    in the same rounds, and the certificate stays exact (non-negative)."""
    ds = shard_dataset(tiny_data, k=4, layout="dense", dtype=jnp.float64)
    debug = DebugParams(debug_iter=20, seed=0)

    def gap_after(sigma):
        p = Params(n=tiny_data.n, num_rounds=20, local_iters=24, lam=1e-2,
                   sigma=sigma)
        _, _, traj = run_cocoa(ds, p, debug, plus=True, quiet=True)
        return traj.records[-1].gap

    g_safe = gap_after(None)
    g_fast = gap_after(2.0)
    assert g_fast >= -1e-12 and g_safe >= -1e-12
    assert g_fast < g_safe


def test_cli_sigma_flag(capsys):
    from cocoa_tpu.cli import parse_args

    cfg, _ = parse_args(["--sigma=4.0"])
    assert cfg.sigma == 4.0
    with pytest.raises(SystemExit):
        parse_args(["--sigmaprime=4"])
