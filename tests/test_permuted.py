"""Random-reshuffling sampler (``--rng=permuted``).

A flag-gated deviation from the reference's with-replacement draws
(CoCoA.scala:151): each shard walks a fresh per-epoch permutation.  The
contract tested here: exact epoch coverage (every coordinate exactly once
per n_local draws, across round and epoch boundaries), determinism and
chunking-invariance (what makes checkpoint/resume exact), decorrelation
across shards, end-to-end solver validity (the duality-gap certificate is
index-stream-independent), and the convergence advantage that justifies
the mode's existence.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from conftest import SMALL_TRAIN  # noqa: E402
from cocoa_tpu.config import DebugParams, Params
from cocoa_tpu.data.sharding import shard_dataset
from cocoa_tpu.solvers import run_cocoa
from cocoa_tpu.solvers.base import IndexSampler


def test_epoch_coverage_exact():
    """Unequal shard sizes, H crossing epoch boundaries mid-round: every
    epoch's draws for a shard are a permutation of range(count)."""
    counts = np.array([7, 13, 16])
    h = 5
    s = IndexSampler("permuted", seed=3, h=h, counts=counts)
    tab = np.asarray(s.chunk_indices(1, 40))          # (40, 3, 5)
    for k, cnt in enumerate(counts):
        stream = tab[:, k, :].reshape(-1)
        n_epochs = len(stream) // cnt
        for e in range(n_epochs):
            ep = stream[e * cnt:(e + 1) * cnt]
            np.testing.assert_array_equal(np.sort(ep), np.arange(cnt))


def test_chunking_invariance_and_determinism():
    """The stream is a pure function of (seed, shard, global step): any
    chunking, any starting round, same tables — resume is exact."""
    counts = np.array([10, 10])
    s1 = IndexSampler("permuted", seed=5, h=7, counts=counts)
    s2 = IndexSampler("permuted", seed=5, h=7, counts=counts)
    whole = np.asarray(s1.chunk_indices(1, 12))
    split = np.concatenate([
        np.asarray(s2.chunk_indices(1, 5)),
        np.asarray(s2.chunk_indices(6, 4)),
        np.asarray(s2.chunk_indices(10, 3)),
    ])
    np.testing.assert_array_equal(split, whole)
    # different seed, different stream
    s3 = IndexSampler("permuted", seed=6, h=7, counts=counts)
    assert not np.array_equal(np.asarray(s3.chunk_indices(1, 12)), whole)


def test_shards_decorrelated():
    counts = np.array([64, 64, 64, 64])
    s = IndexSampler("permuted", seed=0, h=64, counts=counts)
    tab = np.asarray(s.chunk_indices(1, 1))[0]        # (4, 64)
    for a in range(4):
        for b in range(a + 1, 4):
            assert not np.array_equal(tab[a], tab[b])


def test_solver_end_to_end_and_certificate(tiny_data):
    """run_cocoa with rng='permuted': gap certified, α in box, and the
    host and device-loop paths agree (the tables ride the same chunked
    machinery as the other modes)."""
    ds = shard_dataset(tiny_data, k=4, layout="dense", dtype=jnp.float64)
    p = Params(n=tiny_data.n, num_rounds=20, local_iters=20, lam=0.01)
    dbg = DebugParams(debug_iter=10, seed=0)
    w, a, traj = run_cocoa(ds, p, dbg, plus=True, quiet=True,
                           rng="permuted")
    gaps = [r.gap for r in traj.records]
    assert all(g >= -1e-12 for g in gaps)
    assert gaps[-1] < gaps[0]
    assert float(jnp.min(a)) >= 0.0 and float(jnp.max(a)) <= 1.0
    w2, a2, traj2 = run_cocoa(ds, p, dbg, plus=True, quiet=True,
                              rng="permuted", math="fast",
                              device_loop=True)
    np.testing.assert_allclose(np.asarray(w2), np.asarray(w),
                               rtol=1e-6, atol=1e-9)


def test_reshuffling_converges_faster(tiny_data):
    """The reason the mode exists: on the same problem and budget the
    reshuffled stream's duality gap beats with-replacement sampling.
    (Deterministic given the fixed seeds — not a flaky statistical
    assertion; the epsilon-scale measurement is 20 vs 100 rounds.)"""
    ds = shard_dataset(tiny_data, k=4, layout="dense", dtype=jnp.float64)
    p = Params(n=tiny_data.n, num_rounds=15, local_iters=20, lam=0.01)
    dbg = DebugParams(debug_iter=15, seed=0)
    _, _, t_ref = run_cocoa(ds, p, dbg, plus=True, quiet=True,
                            rng="reference")
    _, _, t_perm = run_cocoa(ds, p, dbg, plus=True, quiet=True,
                             rng="permuted")
    assert t_perm.records[-1].gap < t_ref.records[-1].gap


def test_permuted_with_block_kernel(tiny_data):
    """Composes with the block-coordinate inner solver (duplicates within
    a block are impossible inside one epoch, but blocks CROSS epoch
    boundaries where repeats do occur — the equality tiles handle it)."""
    ds = shard_dataset(tiny_data, k=4, layout="dense", dtype=jnp.float64)
    p = Params(n=tiny_data.n, num_rounds=10, local_iters=20, lam=0.01)
    dbg = DebugParams(debug_iter=10, seed=0)
    w_f, _, tf = run_cocoa(ds, p, dbg, plus=True, quiet=True,
                           rng="permuted", math="fast")
    w_b, _, tb = run_cocoa(ds, p, dbg, plus=True, quiet=True,
                           rng="permuted", math="fast", block_size=8)
    np.testing.assert_allclose(np.asarray(w_b), np.asarray(w_f),
                               rtol=1e-9, atol=1e-12)


def test_cli_rng_permuted(capsys):
    from cocoa_tpu import cli

    rc = cli.main([
        f"--trainFile={SMALL_TRAIN}",
        "--numFeatures=9947", "--numSplits=4", "--numRounds=5",
        "--localIterFrac=0.05", "--lambda=.001", "--justCoCoA=true",
        "--debugIter=5", "--rng=permuted", "--mesh=1",
    ])
    assert rc == 0
    assert "CoCoA+" in capsys.readouterr().out

    with pytest.raises(ValueError, match="rng mode"):
        IndexSampler("bogus", 0, 5, np.array([10]))
