"""Mechanical guard on the communication contract (VERDICT r1 item 7).

CoCoA's entire point is ONE O(d) all-reduce per outer round
(CoCoA.scala:47, README title, SURVEY.md §2.3).  Until now that held by
code review only; here the lowered StableHLO of every solver family's
chunked mesh round is inspected and the test fails if a hidden collective
ever creeps into ``chunk_fanout``.

Expected collective census per chunk kernel (C rounds as one lax.scan):

- exactly ONE ``all_reduce`` inside the scan body — the per-round Δw psum
  (the scan body is traced once, so it appears once in the module), and
- exactly ONE ``all_reduce`` outside it — ``invariant_from_varying``'s
  masked psum recovering the replicated w after the scan (per CHUNK, not
  per round; see parallel/fanout.py).

Anything else — an accidental all_gather of shard state, a psum smuggled
into a local solver, a GSPMD-inserted resharding collective — changes the
census and fails the test.
"""

import jax
import jax.numpy as jnp
import pytest

from cocoa_tpu.config import Params
from cocoa_tpu.data.sharding import shard_dataset
from cocoa_tpu.parallel import make_mesh
from cocoa_tpu.parallel.mesh import primal_sharding, sharded_rows

K = 4
H = 10
C = 3  # rounds per chunk; the census must NOT scale with C

COLLECTIVES = ("all_reduce", "all_gather", "all_to_all",
               "collective_permute", "reduce_scatter")


def _census(lowered_text: str) -> dict:
    return {c: lowered_text.count(f"stablehlo.{c}")
            for c in COLLECTIVES if lowered_text.count(f"stablehlo.{c}")}


def _mesh_state(tiny_data, mesh, layout="dense", dtype=jnp.float64):
    ds = shard_dataset(tiny_data, k=K, layout=layout, dtype=dtype,
                       mesh=mesh)
    w = jax.device_put(jnp.zeros(tiny_data.num_features, dtype),
                       primal_sharding(mesh))
    alpha = jax.device_put(jnp.zeros((K, ds.n_shard), dtype),
                           sharded_rows(mesh, extra_dims=1))
    return ds, w, alpha


def _params(tiny_data):
    return Params(n=tiny_data.n, num_rounds=C, local_iters=H, lam=0.01,
                  beta=1.0, gamma=1.0)


@pytest.mark.parametrize("math", ["exact", "fast"])
@pytest.mark.parametrize("alg_key", ["plus", "cocoa", "frozen"])
def test_sdca_chunk_round_has_exactly_one_psum(tiny_data, math, alg_key):
    from cocoa_tpu.solvers.cocoa import _alg_config, _make_chunk_kernel

    mesh = make_mesh(K)
    ds, w, alpha = _mesh_state(tiny_data, mesh)
    p = _params(tiny_data)
    alg = (_alg_config(p, K, None, mode="frozen") if alg_key == "frozen"
           else _alg_config(p, K, alg_key == "plus"))
    kernel = _make_chunk_kernel(mesh, p, K, alg, math=math)
    idxs = jnp.zeros((C, K, H), dtype=jnp.int32)
    txt = jax.jit(kernel).lower(w, alpha, idxs, ds.shard_arrays()).as_text()
    assert _census(txt) == {"all_reduce": 2}, _census(txt)


@pytest.mark.parametrize("chain", ["xla", "pallas_interpret"])
@pytest.mark.parametrize("dtype", [jnp.float64, jnp.float32])
@pytest.mark.parametrize("distinct", [False, True])
def test_block_chunk_round_has_exactly_one_psum(tiny_data, chain, dtype,
                                                distinct):
    """The block-coordinate inner loop (--blockSize) must not change the
    census: its gathers, Gram einsums, Pallas chain, and additive alpha
    scatter are all shard-local — still ONE Δw psum per round.  The f32
    parametrization lowers the FUSED per-block kernel (fused_fits needs
    itemsize 4); f64 lowers the legacy split path.  ``distinct`` adds the
    round-5 one-scatter-per-round α update (merged (y,q,α₀) gather) —
    shard-local too, same census."""
    from cocoa_tpu.ops.pallas_chain import fused_fits
    from cocoa_tpu.solvers.cocoa import _alg_config, _make_chunk_kernel

    if distinct and not (chain == "pallas_interpret"
                         and dtype == jnp.float32):
        pytest.skip("distinct lives on the fused (f32 pallas) path only")
    mesh = make_mesh(K)
    ds, w, alpha = _mesh_state(tiny_data, mesh, dtype=dtype)
    p = _params(tiny_data)
    alg = _alg_config(p, K, True)
    block = 8 if chain == "xla" else 128
    if chain != "xla" and dtype == jnp.float32:
        assert fused_fits(1, block, tiny_data.num_features, 4), \
            "f32 config must exercise the fused kernel"
    kernel = _make_chunk_kernel(mesh, p, K, alg, math="fast",
                                block=block, block_chain=chain,
                                block_distinct=distinct)
    idxs = jnp.zeros((C, K, H), dtype=jnp.int32)
    txt = jax.jit(kernel).lower(w, alpha, idxs, ds.shard_arrays()).as_text()
    assert _census(txt) == {"all_reduce": 2}, _census(txt)


def test_multiplexed_mesh_same_census(tiny_data):
    """Shard multiplexing (K = m·D logical shards on a D-device mesh,
    round 5) must not change the communication contract: the m local
    shards combine IN-DEVICE and the cross-device combine stays the one
    Δw psum per round."""
    from cocoa_tpu.solvers.cocoa import _alg_config, _make_chunk_kernel

    mesh = make_mesh(2)        # K=4 shards on 2 devices -> m=2
    ds = shard_dataset(tiny_data, k=K, layout="dense", dtype=jnp.float64,
                       mesh=mesh)
    w = jax.device_put(jnp.zeros(tiny_data.num_features, jnp.float64),
                       primal_sharding(mesh))
    alpha = jax.device_put(jnp.zeros((K, ds.n_shard), jnp.float64),
                           sharded_rows(mesh, extra_dims=1))
    p = _params(tiny_data)
    kernel = _make_chunk_kernel(mesh, p, K, _alg_config(p, K, True),
                                math="fast")
    idxs = jnp.zeros((C, K, H), dtype=jnp.int32)
    txt = jax.jit(kernel).lower(w, alpha, idxs, ds.shard_arrays()).as_text()
    assert _census(txt) == {"all_reduce": 2}, _census(txt)


@pytest.mark.parametrize("local", [True, False])
def test_sgd_chunk_round_has_exactly_one_psum(tiny_data, local):
    from cocoa_tpu.solvers.sgd import _make_chunk_kernel

    mesh = make_mesh(K)
    ds, w, _ = _mesh_state(tiny_data, mesh)
    p = _params(tiny_data)
    kernel = _make_chunk_kernel(mesh, p, K, local)
    xs = {"idxs": jnp.zeros((C, K, H), dtype=jnp.int32),
          "t": jnp.arange(1.0, C + 1.0)}
    txt = jax.jit(kernel).lower(w, xs, ds.shard_arrays()).as_text()
    assert _census(txt) == {"all_reduce": 2}, _census(txt)


def test_dist_gd_chunk_round_has_exactly_one_psum(tiny_data):
    from cocoa_tpu.solvers.dist_gd import _make_chunk_kernel

    mesh = make_mesh(K)
    ds, w, _ = _mesh_state(tiny_data, mesh)
    p = _params(tiny_data)
    kernel = _make_chunk_kernel(mesh, p, K)
    xs = {"t": jnp.arange(1.0, C + 1.0)}
    txt = jax.jit(kernel).lower(w, xs, ds.shard_arrays()).as_text()
    assert _census(txt) == {"all_reduce": 2}, _census(txt)


def test_sparse_layout_same_census(tiny_data):
    """The padded-CSR layout must not change the communication shape."""
    from cocoa_tpu.solvers.cocoa import _alg_config, _make_chunk_kernel

    mesh = make_mesh(K)
    ds, w, alpha = _mesh_state(tiny_data, mesh, layout="sparse")
    p = _params(tiny_data)
    kernel = _make_chunk_kernel(mesh, p, K, _alg_config(p, K, True),
                                math="exact")
    idxs = jnp.zeros((C, K, H), dtype=jnp.int32)
    txt = jax.jit(kernel).lower(w, alpha, idxs, ds.shard_arrays()).as_text()
    assert _census(txt) == {"all_reduce": 2}, _census(txt)
