"""Hot/cold column split (round 10 tentpole) — the hybrid sparse layout:
MXU hot panel + cold residual streams (data/hybrid.py, docs/DESIGN.md
§3b-vi), consumed by the row accessors (ops/rows.py), the sparse
block-chain path (the panel Gram matmul joining the residual stream
merges in local_sdca_block_batched), and the sequential sparse kernel
(per-step panel rows through VMEM, ops/pallas_sparse.py).

The split partitions each row's nonzeros by column — a permutation of
every per-nonzero sum — so the contract mirrors tests/test_sparse_block.py:
the hybrid paths consume the SAME sampled index stream as the sequential
fast path on the UNSPLIT layout and are identical to it in real
arithmetic; trajectory parity (f64 at ~1e-12, f32 at fp tolerance) is
pinned in CPU interpret mode across the block, sequential, and
SMEM-segmented split-fallback branches, all three SDCA modes, the driver
integration, and the `--hotCols` resolution (auto coverage target,
explicit HBM accounting, `off` as the bit-exact stream control).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from cocoa_tpu.config import DebugParams, Params
from cocoa_tpu.data import hybrid
from cocoa_tpu.data.sharding import shard_dataset
from cocoa_tpu.data.synth import synth_sparse
from cocoa_tpu.ops.local_sdca import local_sdca_block_batched, local_sdca_fast
from cocoa_tpu.ops.pallas_sparse import pallas_sparse_sdca_round
from cocoa_tpu.ops.rows import shard_margins
from cocoa_tpu.solvers import run_cocoa
from cocoa_tpu.utils.prng import sample_indices_per_shard

K = 4
N_HOT = 256


@pytest.fixture(scope="module")
def zipf_data():
    """Distribution-faithful rcv1-like synth (Zipf columns, log-normal row
    lengths, tf-idf values) at CI scale — the regime the split exists for."""
    return synth_sparse(300, 800, nnz_mean=20, seed=3)


def _pair(data, dtype=jnp.float64, n_hot=N_HOT, k=K):
    """(unsplit, hybrid) shardings of the same data."""
    plain = shard_dataset(data, k=k, layout="sparse", dtype=dtype)
    hyb = shard_dataset(data, k=k, layout="sparse", dtype=dtype,
                        hot_cols=n_hot)
    return plain, hyb


def _compare_vs_fast(da_h, dw_h, plain, w, alpha, idxs, n, mode, sigma,
                     rtol, atol):
    """Pin hybrid outputs against the sequential fast path on the UNSPLIT
    layout — the same oracle the round-6 sparse-block kernel was pinned
    against."""
    sa = plain.shard_arrays()
    d = w.shape[0]
    for s in range(alpha.shape[0]):
        shard = {kk: v[s] for kk, v in sa.items()}
        da_f, dw_f = local_sdca_fast(
            shard_margins(w, shard), alpha[s], shard, idxs[s], 0.01, n,
            jnp.zeros(d, w.dtype), mode=mode, sigma=sigma,
        )
        np.testing.assert_allclose(np.asarray(da_h[s]), np.asarray(da_f),
                                   rtol=rtol, atol=atol)
        np.testing.assert_allclose(np.asarray(dw_h[s]), np.asarray(dw_f),
                                   rtol=rtol, atol=atol)


# --------------------------------------------------------------------------
# the layout itself
# --------------------------------------------------------------------------


def test_split_is_exact_partition(zipf_data):
    """Hot panel + cold residual reconstruct exactly the unsplit rows —
    the split moves nonzeros, it never changes or duplicates them."""
    plain, hyb = _pair(zipf_data)
    d = zipf_data.num_features
    spi0, spv0 = np.asarray(plain.sp_indices), np.asarray(plain.sp_values)
    spi1, spv1 = np.asarray(hyb.sp_indices), np.asarray(hyb.sp_values)
    xh, hc = np.asarray(hyb.X_hot), np.asarray(hyb.hot_cols)
    assert hyb.n_hot == N_HOT and hc.shape == (K, N_HOT)
    # the residual width is the max COLD nnz — strictly under the unsplit
    # width on Zipf data
    assert spi1.shape[-1] < spi0.shape[-1]
    for s in range(K):
        for i in range(hyb.n_shard):
            full = np.zeros(d)
            np.add.at(full, spi0[s, i], spv0[s, i])
            split = np.zeros(d)
            np.add.at(split, spi1[s, i], spv1[s, i])
            np.add.at(split, hc[s], xh[s, i])
            np.testing.assert_array_equal(split, full)
    # hot ids are the top-count columns of the measured histogram
    counts = hybrid.column_counts(zipf_data)
    expect = hybrid.hottest_columns(counts, N_HOT)
    np.testing.assert_array_equal(hc[0][:len(expect)], expect)


def test_hot_cols_off_is_bit_exact_control(zipf_data):
    """hot_cols=0 must leave every array of today's stream layout
    untouched — the A/B control the flag promises."""
    plain = shard_dataset(zipf_data, k=K, layout="sparse")
    off = shard_dataset(zipf_data, k=K, layout="sparse", hot_cols=0)
    assert off.X_hot is None and off.hot_cols is None
    np.testing.assert_array_equal(np.asarray(off.sp_indices),
                                  np.asarray(plain.sp_indices))
    np.testing.assert_array_equal(np.asarray(off.sp_values),
                                  np.asarray(plain.sp_values))


def test_shard_margins_and_eval_match(zipf_data):
    """The hybrid row accessors reproduce the unsplit margins to f64
    reassociation tolerance (the split permutes each row's sum)."""
    plain, hyb = _pair(zipf_data)
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=zipf_data.num_features))
    sa_p, sa_h = plain.shard_arrays(), hyb.shard_arrays()
    for s in range(K):
        m0 = shard_margins(w, {kk: v[s] for kk, v in sa_p.items()})
        mh = shard_margins(w, {kk: v[s] for kk, v in sa_h.items()})
        np.testing.assert_allclose(np.asarray(mh), np.asarray(m0),
                                   rtol=1e-12, atol=1e-12)


def test_resolve_hot_cols(zipf_data):
    """--hotCols resolution: auto hits the coverage target under the HBM
    budget, explicit widths pad to lanes, oversized panels are REJECTED
    with the accounting, off resolves to the stream layout."""
    n_hot, stats = hybrid.resolve_hot_cols("auto", zipf_data, K,
                                           jnp.float32)
    assert n_hot % 128 == 0 and n_hot > 0
    assert stats["coverage"] >= hybrid.HOT_COVERAGE_TARGET
    assert stats["panel_bytes"] > 0
    assert stats["residual_mean_nnz"] < 20  # the tail is a fraction

    n_off, stats_off = hybrid.resolve_hot_cols("off", zipf_data, K,
                                               jnp.float32)
    assert n_off == 0 and stats_off["hot_cols"] == 0

    n_x, stats_x = hybrid.resolve_hot_cols("100", zipf_data, K, jnp.float32)
    assert n_x == 128  # padded to whole lane blocks

    # resolve and build must stay in lockstep: both derive the hot set
    # from hybrid.hottest_columns(column_counts(data), n), so the
    # manifest's residual stats describe the layout actually built
    ds = shard_dataset(zipf_data, k=K, layout="sparse", hot_cols=n_hot)
    assert int(ds.sp_indices.shape[-1]) == stats["residual_max_nnz"]

    with pytest.raises(ValueError, match="HBM|budget"):
        hybrid.resolve_hot_cols("256", zipf_data, K, jnp.float32,
                                budget=1024)
    with pytest.raises(ValueError, match="auto|off"):
        hybrid.resolve_hot_cols("garbage", zipf_data, K, jnp.float32)

    # auto under a tiny budget: clamps down, and to 0 when nothing fits
    n_clamped, _ = hybrid.resolve_hot_cols(
        "auto", zipf_data, K, jnp.float32,
        budget=hybrid.panel_bytes(128, K, 80, 4))
    assert n_clamped == 128
    n_none, _ = hybrid.resolve_hot_cols("auto", zipf_data, K, jnp.float32,
                                        budget=1024)
    assert n_none == 0


def test_hot_cols_rejects_dense_layout(zipf_data):
    with pytest.raises(ValueError, match="sparse"):
        shard_dataset(zipf_data, k=K, layout="dense", hot_cols=128)


# --------------------------------------------------------------------------
# the hybrid BLOCK branch (panel Gram matmul + residual stream merges)
# --------------------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.parametrize("mode,sigma", [
    ("cocoa", 1.0),
    # tier-1 budget (rounds 22/24): every arm now rides -m slow — the
    # dedicated CI parity step runs this file unfiltered, so the parity
    # contract keeps its own CI signal
    pytest.param("plus", 4.0, marks=pytest.mark.slow),
    pytest.param("frozen", 1.0, marks=pytest.mark.slow)])
def test_hybrid_block_matches_fast(zipf_data, mode, sigma):
    """f32 interpret-mode parity vs the sequential fast path on the
    UNSPLIT layout — masked tail (H=37 vs B=128) and duplicate draws
    included, all three SDCA modes."""
    plain, hyb = _pair(zipf_data, dtype=jnp.float32)
    rng = np.random.default_rng(5)
    d = zipf_data.num_features
    w = jnp.asarray(rng.normal(size=d) * 0.1, jnp.float32)
    alpha = jnp.asarray(
        np.clip(rng.normal(size=(K, hyb.n_shard)) * 0.3 + 0.3, 0, 1),
        jnp.float32,
    )
    idxs = jnp.asarray(
        sample_indices_per_shard(7, range(1, 2), 37, hyb.counts)[:, 0, :]
    )
    da_h, dw_h = local_sdca_block_batched(
        w, alpha, hyb.shard_arrays(), idxs, 0.01, zipf_data.n, mode=mode,
        sigma=sigma, block=128, interpret=True, sparse_gram=True,
    )
    _compare_vs_fast(da_h, dw_h, plain, w, alpha, idxs, zipf_data.n,
                     mode, sigma, rtol=2e-4, atol=1e-6)


@pytest.mark.slow
def test_hybrid_block_f64(zipf_data):
    """f64 pins the algebra at ~1e-12 — the same 'bit-comparable at f64'
    contract the round-6 kernel carries (fp reassociation is the entire
    difference; the split adds no math)."""
    plain, hyb = _pair(zipf_data, dtype=jnp.float64)
    rng = np.random.default_rng(11)
    d = zipf_data.num_features
    w = jnp.asarray(rng.normal(size=d) * 0.1)
    alpha = jnp.asarray(
        np.clip(rng.normal(size=(K, hyb.n_shard)) * 0.3 + 0.3, 0, 1))
    idxs = jnp.asarray(
        sample_indices_per_shard(3, range(1, 2), 37, hyb.counts)[:, 0, :]
    )
    da_h, dw_h = local_sdca_block_batched(
        w, alpha, hyb.shard_arrays(), idxs, 0.01, zipf_data.n, mode="plus",
        sigma=4.0, block=128, interpret=True, sparse_gram=True,
    )
    _compare_vs_fast(da_h, dw_h, plain, w, alpha, idxs, zipf_data.n,
                     "plus", 4.0, rtol=1e-9, atol=1e-12)


@pytest.mark.slow
def test_hybrid_block_split_fallback_segmented(zipf_data, monkeypatch):
    """The SMEM split-fallback branch: shrink the budget so the residual
    Gram runs in (S, S) row-segment tiles, and span two blocks (H=200)
    so the Δw carry — including the separately-carried hot Δw — crosses
    block boundaries."""
    import cocoa_tpu.ops.pallas_sparse as ps

    plain, hyb = _pair(zipf_data, dtype=jnp.float32)
    w_nnz = int(hyb.sp_indices.shape[-1])
    group = min(ps.GROUP, w_nnz)
    w_r = -(-w_nnz // group) * group
    monkeypatch.setattr(ps, "SMEM_IDX_BUDGET", 16 * 32 * w_r)
    assert ps.seg_rows(128, w_nnz) == 32
    rng = np.random.default_rng(5)
    d = zipf_data.num_features
    w = jnp.asarray(rng.normal(size=d) * 0.1, jnp.float32)
    alpha = jnp.asarray(
        np.clip(rng.normal(size=(K, hyb.n_shard)) * 0.3 + 0.3, 0, 1),
        jnp.float32,
    )
    idxs = jnp.asarray(
        sample_indices_per_shard(7, range(1, 2), 200, hyb.counts)[:, 0, :]
    )
    da_h, dw_h = local_sdca_block_batched(
        w, alpha, hyb.shard_arrays(), idxs, 0.01, zipf_data.n, mode="plus",
        sigma=4.0, block=128, interpret=True, sparse_gram=True,
    )
    _compare_vs_fast(da_h, dw_h, plain, w, alpha, idxs, zipf_data.n,
                     "plus", 4.0, rtol=2e-4, atol=1e-6)


def test_hybrid_densified_fallback(zipf_data):
    """The densified (non-sparse-Gram) block fallback gathers hybrid rows
    correctly too: hot panel scatters join the residual scatter in the
    (K, B, d) tile."""
    plain, hyb = _pair(zipf_data, dtype=jnp.float64)
    rng = np.random.default_rng(2)
    d = zipf_data.num_features
    w = jnp.asarray(rng.normal(size=d) * 0.1)
    alpha = jnp.asarray(
        np.clip(rng.normal(size=(K, hyb.n_shard)) * 0.3 + 0.3, 0, 1))
    idxs = jnp.asarray(
        sample_indices_per_shard(7, range(1, 2), 24, hyb.counts)[:, 0, :]
    )
    da_h, dw_h = local_sdca_block_batched(
        w, alpha, hyb.shard_arrays(), idxs, 0.01, zipf_data.n, mode="plus",
        sigma=4.0, block=128, interpret=True, sparse_gram=False,
    )
    _compare_vs_fast(da_h, dw_h, plain, w, alpha, idxs, zipf_data.n,
                     "plus", 4.0, rtol=1e-9, atol=1e-12)


# --------------------------------------------------------------------------
# the hybrid SEQUENTIAL kernel branch
# --------------------------------------------------------------------------


@pytest.mark.slow
@pytest.mark.parametrize("mode,sigma", [
    ("cocoa", 1.0),
    # tier-1 budget (rounds 22/24): every arm now rides -m slow — the
    # dedicated CI parity step runs this file unfiltered, so the parity
    # contract keeps its own CI signal
    pytest.param("plus", 4.0, marks=pytest.mark.slow),
    pytest.param("frozen", 1.0, marks=pytest.mark.slow)])
def test_hybrid_seq_kernel_matches_fast(zipf_data, mode, sigma):
    """The sequential sparse kernel's hybrid branch (per-step panel rows
    through VMEM + residual streams), f64 interpret mode, all modes."""
    plain, hyb = _pair(zipf_data, dtype=jnp.float64)
    rng = np.random.default_rng(0)
    d = zipf_data.num_features
    w = jnp.asarray(rng.normal(size=d) * 0.1)
    alpha = jnp.asarray(
        np.clip(rng.normal(size=(K, hyb.n_shard)) * 0.3 + 0.3, 0, 1))
    idxs = jnp.asarray(
        sample_indices_per_shard(7, range(1, 2), 37, hyb.counts)[:, 0, :]
    )
    sa = hyb.shard_arrays()
    dw_h, a_h = pallas_sparse_sdca_round(
        w, alpha, sa["sp_indices"], sa["sp_values"], sa["labels"],
        sa["sq_norms"], idxs, 0.01, zipf_data.n, mode=mode, sigma=sigma,
        interpret=True, hot_cols=sa["hot_cols"], hot_panel=sa["X_hot"],
    )
    _compare_vs_fast(a_h - alpha, dw_h, plain, w, alpha, idxs, zipf_data.n,
                     mode, sigma, rtol=1e-9, atol=1e-12)


@pytest.mark.slow
def test_hybrid_seq_kernel_segmented(zipf_data, monkeypatch):
    """SMEM segmentation of the sequential hybrid round: the hot Δw must
    carry across segment boundaries exactly like [w | Δw] does."""
    import cocoa_tpu.ops.pallas_sparse as ps

    monkeypatch.setattr(ps, "SMEM_IDX_BUDGET", 8 * K * 32 * 10)
    plain, hyb = _pair(zipf_data, dtype=jnp.float64)
    rng = np.random.default_rng(0)
    d = zipf_data.num_features
    w = jnp.asarray(rng.normal(size=d) * 0.1)
    alpha = jnp.asarray(
        np.clip(rng.normal(size=(K, hyb.n_shard)) * 0.3 + 0.3, 0, 1))
    idxs = jnp.asarray(
        sample_indices_per_shard(9, range(1, 2), 64, hyb.counts)[:, 0, :]
    )
    sa = hyb.shard_arrays()
    dw_h, a_h = pallas_sparse_sdca_round(
        w, alpha, sa["sp_indices"], sa["sp_values"], sa["labels"],
        sa["sq_norms"], idxs, 0.01, zipf_data.n, mode="plus", sigma=4.0,
        interpret=True, hot_cols=sa["hot_cols"], hot_panel=sa["X_hot"],
    )
    _compare_vs_fast(a_h - alpha, dw_h, plain, w, alpha, idxs, zipf_data.n,
                     "plus", 4.0, rtol=1e-9, atol=1e-12)


# --------------------------------------------------------------------------
# dispatch + fits
# --------------------------------------------------------------------------


def test_hybrid_fits_accounting():
    from cocoa_tpu.ops.pallas_sparse import (
        hybrid_fits, sparse_chain_fits, sparse_kernel_fits,
    )

    # rcv1-flagship shapes: the RESIDUAL width (214 at 75% coverage) only
    # loosens the stream constraint the unsplit width already passes
    assert sparse_chain_fits(8, 2544, 47236, 548, 128, 4)
    assert hybrid_fits(8, 2544, 47236, 214, 128, 2048, 4)
    assert not hybrid_fits(8, 2544, 47236, 214, 128, 0, 4)     # no panel
    assert not hybrid_fits(8, 2544, 47236, 214, 128, 100, 4)   # unaligned
    assert not hybrid_fits(8, 2544, 47236, 5000, 128, 2048, 4)  # streams
    # sequential kernel: the panel adds VMEM; a huge panel fails the fit
    assert sparse_kernel_fits(8, 2544, 47236, 214, 253, 4, n_hot=2048)
    assert not sparse_kernel_fits(8, 2544, 47236, 214, 253, 4,
                                  n_hot=1 << 20)


def test_auto_block_size_hybrid(zipf_data):
    """--blockSize=auto accepts the hybrid layout through hybrid_fits
    (the residual streams are narrower, so a split layout never resolves
    worse than the unsplit one)."""
    from cocoa_tpu.solvers.cocoa import auto_block_size

    plain, hyb = _pair(zipf_data, dtype=jnp.float32)
    assert auto_block_size(hyb, K, jnp.float32) == \
        auto_block_size(plain, K, jnp.float32) == 128


# --------------------------------------------------------------------------
# driver + eval integration
# --------------------------------------------------------------------------


@pytest.mark.slow
def test_hybrid_through_driver_block(zipf_data):
    """run_cocoa on the hybrid layout (sparse-Gram block path) reproduces
    the unsplit fast-path trajectory, including the final duality gap."""
    plain, hyb = _pair(zipf_data, dtype=jnp.float32)
    p = Params(n=zipf_data.n, num_rounds=6, local_iters=20, lam=0.01)
    dbg = DebugParams(debug_iter=3, seed=0)
    w_f, a_f, traj_f = run_cocoa(plain, p, dbg, plus=True, quiet=True,
                                 math="fast", pallas=False)
    w_h, a_h, traj_h = run_cocoa(hyb, p, dbg, plus=True, quiet=True,
                                 math="fast", block_size=128,
                                 block_chain="pallas_interpret",
                                 block_sparse_gram=True)
    np.testing.assert_allclose(np.asarray(w_h), np.asarray(w_f),
                               rtol=2e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(a_h), np.asarray(a_f),
                               rtol=2e-4, atol=1e-6)
    assert traj_h.records[-1].gap == pytest.approx(
        traj_f.records[-1].gap, rel=1e-3)


def test_hybrid_through_driver_fast_xla(zipf_data):
    """The plain fast path (no kernels) handles the hybrid layout through
    the row accessors alone — the structural guarantee that oversized
    panels can always fall back without losing the layout."""
    plain, hyb = _pair(zipf_data, dtype=jnp.float64)
    p = Params(n=zipf_data.n, num_rounds=4, local_iters=12, lam=0.01)
    dbg = DebugParams(debug_iter=2, seed=0)
    w_f, a_f, _ = run_cocoa(plain, p, dbg, plus=True, quiet=True,
                            math="fast", pallas=False)
    w_h, a_h, _ = run_cocoa(hyb, p, dbg, plus=True, quiet=True,
                            math="fast", pallas=False)
    np.testing.assert_allclose(np.asarray(w_h), np.asarray(w_f),
                               rtol=1e-9, atol=1e-12)
    np.testing.assert_allclose(np.asarray(a_h), np.asarray(a_f),
                               rtol=1e-9, atol=1e-12)


def test_eval_dense_auto_trained_state_bit_identical(zipf_data):
    """--evalDense on/off over the SAME hybrid layout: eval routing (dense
    twin vs hot panel + residual stream) may change logged metrics only by
    rounding order — the TRAINED (w, alpha) must be bit-identical, proving
    no training path reads either eval structure."""
    hyb = shard_dataset(zipf_data, k=K, layout="sparse",
                        dtype=jnp.float64, hot_cols=N_HOT)
    hyb_twin = shard_dataset(zipf_data, k=K, layout="sparse",
                             dtype=jnp.float64, hot_cols=N_HOT,
                             eval_dense=True)
    p = Params(n=zipf_data.n, num_rounds=4, local_iters=8, lam=0.01)
    dbg = DebugParams(debug_iter=2, seed=0)
    w_p, a_p, traj_p = run_cocoa(hyb, p, dbg, plus=True, quiet=True,
                                 math="fast")
    w_t, a_t, traj_t = run_cocoa(hyb_twin, p, dbg, plus=True, quiet=True,
                                 math="fast")
    np.testing.assert_array_equal(np.asarray(w_t), np.asarray(w_p))
    np.testing.assert_array_equal(np.asarray(a_t), np.asarray(a_p))
    for rp, rt in zip(traj_p.records, traj_t.records):
        np.testing.assert_allclose(rt.gap, rp.gap, rtol=1e-12, atol=1e-12)


def test_subgradient_and_sgd_handle_hybrid(zipf_data):
    """DistGD's vectorized subgradient pass (and with it the SGD family's
    shard_margins) reproduces the unsplit result on the hybrid layout."""
    from cocoa_tpu.ops.subgradient import subgradient_pass

    plain, hyb = _pair(zipf_data, dtype=jnp.float64)
    rng = np.random.default_rng(1)
    w = jnp.asarray(rng.normal(size=zipf_data.num_features))
    sa_p, sa_h = plain.shard_arrays(), hyb.shard_arrays()
    for s in range(K):
        g_p = subgradient_pass(w, {kk: v[s] for kk, v in sa_p.items()}, 0.01)
        g_h = subgradient_pass(w, {kk: v[s] for kk, v in sa_h.items()}, 0.01)
        np.testing.assert_allclose(np.asarray(g_h), np.asarray(g_p),
                                   rtol=1e-9, atol=1e-12)


def test_cli_hot_cols_end_to_end(tmp_path, capsys):
    """--hotCols=auto through the CLI: the resolution note prints the
    panel accounting, the run completes, and --hotCols on a dense layout
    is rejected."""
    from cocoa_tpu import cli
    from cocoa_tpu.data.synth import write_libsvm

    path = str(tmp_path / "train.dat")
    write_libsvm(synth_sparse(200, 600, nnz_mean=15, seed=1), path)
    rc = cli.main([
        f"--trainFile={path}", "--numFeatures=600", "--numSplits=4",
        "--numRounds=3", "--localIterFrac=0.2", "--lambda=.01",
        "--debugIter=3", "--mesh=1", "--hotCols=auto", "--evalDense=auto",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "hotCols=auto: panel" in out
    assert "nonzero coverage" in out and "MiB HBM" in out
    assert "evalDense=auto:" in out

    rc = cli.main([
        f"--trainFile={path}", "--numFeatures=600", "--layout=dense",
        "--hotCols=64",
    ])
    assert rc == 2
    assert "sparse layout" in capsys.readouterr().err
