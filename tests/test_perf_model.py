"""Roofline-accounting guard (ISSUE 2 satellite / VERDICT r5 weak #1).

benchmarks/perf.py's FLOP/byte models are the denominator of every MFU,
HBM-floor, and bound-classification claim in RESULTS.md/KERNELS.md — a
kernel edit that changes what the code actually moves, without the model
following, silently desyncs the roofline story from reality.  These tests
recompute the models for the block and sparse-block configs (the paths
PR 1 and the pipelined round touch) against HAND-COMPUTED fixtures:
every expected number below is literal arithmetic derived independently
from the accounting contract in the perf.py docstrings, not a call back
into the code under test.  A legitimate kernel/model change updates the
fixture consciously; an accidental desync fails here.
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "benchmarks"))

import perf  # noqa: E402


def test_block_model_epsilon_fixture():
    """Dense block path at the epsilon flagship shape (n=400000, d=2000,
    K=8, H=5000, B=128).  Contract (perf.py "block"): per step one
    row·(w+σΔw) dot + one axpy (useful 4·d), the in-tile margin dot
    (2·d), and the B·d Gram MACs that buy the MXU formulation (physical
    only); HBM reads each sampled row once."""
    steps = 8 * 5000                       # = 40_000 coordinate steps
    useful = 4.0 * 2000 * steps            # = 3.2e8  (dot + axpy)
    margins = 2.0 * 2000 * steps           # = 1.6e8  (in-tile x·v)
    gram = 2.0 * 128 * 2000 * steps        # = 2.048e10 (B·d MACs/step)
    row_bytes = 4 * 2000                   # f32 dense row = 8000 B
    m = perf.sdca_round_model(400_000, 2000, 8, 5000, layout="dense",
                              path="block", block=128)
    assert m["useful_flops"] == useful + margins == 4.8e8
    assert m["physical_flops"] == useful + margins + gram == 2.096e10
    assert m["hbm_bytes"] == steps * row_bytes == 3.2e8


def test_block_model_sparse_densify_fixture():
    """Sparse layout through the DENSIFIED block path: the tile
    write+read is B·d dense (3 passes: densify write, Gram/margins read,
    Δw-apply read) — the traffic that makes this path lose on rcv1."""
    steps = 8 * 253                        # = 2024
    m = perf.sdca_round_model(20_242, 47_236, 8, 253, layout="sparse",
                              nnz=75, path="block", block=128)
    assert m["hbm_bytes"] == steps * 47_236 * 4 * 3
    assert m["useful_flops"] == (4.0 * 75 + 2.0 * 75) * steps


def test_sparse_block_model_rcv1_fixture():
    """In-kernel CSR Gram path at the rcv1 shape (W=560 padded-CSR
    width).  Contract (perf.py "sparse-block"): useful work as the dense
    block path on nnz=75; every SMEM-addressed pick/scatter is a
    (1, 128)-lane op (128x physical); HBM moves the CSR streams once per
    segment pair plus the lane-blocked [w|Δw] operand per tile call.

    Hand derivation of the segmentation at B=128, W=560 (GROUP=32,
    ops/pallas_sparse.seg_rows): s=32 rows/segment -> ns=4 segments,
    pairs = 4·5/2 = 10; d_pad = ceil(47236/128)·128 = 47360,
    wd_bytes = 2·47360·4 = 378_880; blocks/round = 2024/128 = 15.8125."""
    steps = 8 * 253                        # = 2024
    useful = 4.0 * 75 * steps              # = 607_200
    margins = 2.0 * 75 * steps             # = 303_600
    gram = 2.0 * 128 * 75 * steps          # = 38_860_800
    row_bytes = 2 * 4 * 75                 # CSR idx+val per nonzero
    ns, pairs = 4, 10
    wd_bytes = 2 * 47_360 * 4
    blocks = steps / 128
    hbm = (steps * row_bytes * (pairs + ns) / ns
           + blocks * (pairs * wd_bytes + ns * 2 * wd_bytes))
    m = perf.sdca_round_model(20_242, 47_236, 8, 253, layout="sparse",
                              nnz=75, path="sparse-block", block=128,
                              max_nnz=560)
    assert m["useful_flops"] == useful + margins == 910_800
    assert m["physical_flops"] == (useful + margins + gram) * 128 \
        == 5_090_764_800
    assert m["hbm_bytes"] == hbm == 112_089_120


def test_pallas_and_fast_models_differ_by_margins_pass():
    """The "fast" path pays a whole-shard X·w margins pass (2·n·d FLOPs,
    n rows of HBM) that the round-4+ in-kernel paths retired in favor of
    a 2·d margin dot per sampled step — the distinction that fixed the
    impossible pre-round-4 floors."""
    n, d, k, h = 400_000, 2000, 8, 5000
    fast = perf.sdca_round_model(n, d, k, h, path="fast")
    pall = perf.sdca_round_model(n, d, k, h, path="pallas")
    steps = k * h                          # = 40_000
    assert fast["useful_flops"] - pall["useful_flops"] \
        == 2.0 * n * d - 2.0 * d * steps   # whole-X pass vs per-step dot
    assert fast["hbm_bytes"] - pall["hbm_bytes"] == n * d * 4


def test_eval_flops_fixture():
    """One gap+test evaluation: full-data margins (2·(n+t)·nnz) + O(n)
    loss reductions (5 FLOPs/row in the contract)."""
    assert perf.eval_flops(1000, 50, test_n=200) \
        == 2.0 * 1200 * 50 + 5.0 * 1200


def test_hybrid_seq_model_rcv1_fixture():
    """Hot/cold split, sequential kernel, at the rcv1 shape with n_hot=2048
    and 75% coverage.  Contract (perf.py "hybrid-seq"): useful work is the
    unchanged reference math (6·nnz per step); physically the RESIDUAL
    nnz·(1−cov) pays the 128x stream price ((4+2)·nnz_cold·128) while the
    panel adds 6·n_hot whole-lane VPU MACs; HBM moves the residual CSR
    streams (2·4·nnz_cold) plus the gathered panel row twice (write +
    kernel read)."""
    steps = 8 * 253                        # = 2024
    nnz_cold = 75 * 0.25                   # = 18.75 mean residual nnz
    useful = 6.0 * 75 * steps              # = 910_800
    physical = (6.0 * nnz_cold * 128 + 6.0 * 2048) * steps
    hbm = steps * (2 * 4 * nnz_cold + 2 * 2048 * 4)
    m = perf.sdca_round_model(20_242, 47_236, 8, 253, layout="sparse",
                              nnz=75, path="hybrid-seq", n_hot=2048,
                              coverage=0.75)
    assert m["useful_flops"] == useful
    assert m["physical_flops"] == physical == 54_016_512.0
    assert m["hbm_bytes"] == hbm == 33_464_816.0


def test_hybrid_block_model_rcv1_fixture():
    """Hot/cold split, block path, rcv1 shape (RESIDUAL width 214 at 75%
    coverage).  Hand derivation of the residual segmentation at B=128:
    GROUP-rounded width 224 → 16·128·224 = 458 752 B fits the 512 KB SMEM
    budget WHOLE, so s=128, ns=1, pairs=1 — the split also collapses the
    unsplit layout's 4-segment/10-pair Gram tiling.  Panel adds per step
    2·B·n_hot Gram + 4·n_hot margin/apply MACs (MXU-rate, no 128x), and
    the tile crosses HBM 4x (gather write + 3 einsum reads)."""
    steps = 8 * 253
    nnz_cold = 75 * 0.25
    gram_cold = 2.0 * 128 * nnz_cold       # per step
    physical = ((6.0 * nnz_cold + gram_cold) * 128
                + 2.0 * 128 * 2048 + 4.0 * 2048) * steps
    cold_bytes = 2 * 4 * nnz_cold
    ns, pairs = 1, 1
    wd_bytes = 2 * 47_360 * 4
    blocks = steps / 128
    hbm = (steps * cold_bytes * (pairs + ns) / ns
           + blocks * (pairs * wd_bytes + ns * 2 * wd_bytes)
           + steps * 4 * 2048 * 4)
    m = perf.sdca_round_model(20_242, 47_236, 8, 253, layout="sparse",
                              nnz=75, path="hybrid-block", block=128,
                              max_nnz=214, n_hot=2048, coverage=0.75)
    assert m["useful_flops"] == 6.0 * 75 * steps
    assert m["physical_flops"] == physical == 2_350_430_720.0
    assert m["hbm_bytes"] == hbm == 84_902_752.0


def test_latency_predictor_calibration_and_hybrid_target():
    """The calibrated slot-latency predictor reproduces the MEASURED
    6.16 ms rcv1 stream round by construction (TRACE.md: 2024 steps ×
    96 GROUP-rounded slots), and predicts the hybrid sequential round
    under the 3.5 ms acceptance bar: 75% coverage drops the mean
    residual to 18.4 nnz → ONE 32-slot group per step, plus 2·(2048/128)
    panel lane-row ops."""
    steps = 8 * 253
    assert perf.predict_sparse_round_ms(steps, 73.6) \
        == pytest.approx(6.16, rel=1e-12)
    slot_ns = 6.16e6 / (steps * 96)
    expect = steps * (32 * slot_ns + 2 * (2048 / 128) * 3.0) * 1e-6
    hyb = perf.predict_sparse_round_ms(steps, 73.6, n_hot=2048,
                                       coverage=0.75)
    assert hyb == pytest.approx(expect, rel=1e-12)
    assert hyb < 3.5                       # the ISSUE 5 acceptance bar


def test_unknown_path_rejected():
    with pytest.raises(ValueError, match="unknown path"):
        perf.sdca_round_model(10, 10, 1, 1, path="warp")


def test_predict_accel_rounds_fixture():
    """Hand-computed accelerated floor (perf.py predict_accel_rounds).

    Fixture: gap0 = 1, target = e⁻⁸ (so decades = −8 exactly), plain
    rounds = 800 ⇒ per-round rate q = e^(−8/800) = e^(−0.01)
    = 0.990049834…; 1 − q = 0.00995016625…, √(1−q) = 0.0997505201…,
    q_acc = 0.9002494799…, ln(q_acc) = −0.105083567…;
    −8 / ln(q_acc) = 76.1299…, ×1.1 restart inflation = 83.74…,
    ceil = 84."""
    import math

    gap0, target, r_plain = 1.0, math.exp(-8.0), 800
    assert perf.predict_accel_rounds(r_plain, gap0, target) == 84
    # no restart inflation: ceil(76.1299...) = 77
    assert perf.predict_accel_rounds(r_plain, gap0, target,
                                     restart_overhead=0.0) == 77
    # the floor is a STRICT improvement and scales with conditioning:
    # a slower plain run (worse q) accelerates by a bigger factor
    fast = perf.predict_accel_rounds(100, 1.0, 1e-4)
    slow = perf.predict_accel_rounds(1600, 1.0, 1e-4)
    assert fast < 100 and slow < 1600
    assert 1600 / slow > 100 / fast


def test_predict_accel_rounds_validations():
    with pytest.raises(ValueError, match="gap_target"):
        perf.predict_accel_rounds(100, 1e-4, 1.0)
    with pytest.raises(ValueError, match="rounds_plain"):
        perf.predict_accel_rounds(0, 1.0, 1e-4)


def test_ingest_model_whole_fixture():
    """whole mode, hand-computed: one full-file parse at the calibrated
    rate, full host CSR held.  file 90 MB (exactly 1 s at 90e6 B/s),
    n=10_000, nnz=750_000, d=47_236."""
    m = perf.ingest_model(90_000_000, 10_000, 750_000, 4,
                          mode="whole", d=47_236)
    assert m["bytes_read"] == 90_000_000.0
    assert m["parse_seconds"] == pytest.approx(1.0)
    # 8n labels + 8(n+1) indptr + 4nnz indices + 8nnz values
    assert m["csr_peak_bytes"] == (8 * 10_000 + 8 * 10_001
                                   + 4 * 750_000 + 8 * 750_000)


def test_ingest_model_stream_fixture():
    """stream mode, hand-computed at P=4: 2·(file/4) parsed, the held CSR
    shrinks to CSR/4 + the global index (row_off + row_nnz + hist)."""
    m = perf.ingest_model(90_000_000, 10_000, 750_000, 4,
                          mode="stream", d=47_236)
    assert m["bytes_read"] == 45_000_000.0
    exchange = 3 * (8 * 10_000 + 8 * 47_236)
    assert m["parse_seconds"] == pytest.approx(
        45_000_000 / 90e6 + exchange / 50e6)
    csr = 8 * 10_000 + 8 * 10_001 + 4 * 750_000 + 8 * 750_000
    index = 8 * 10_001 + 8 * 10_000 + 8 * 47_236
    assert m["csr_peak_bytes"] == pytest.approx(csr / 4 + index)


def test_ingest_model_ratios_and_validation():
    """The model's headline ratios: at P processes the streamed parse
    work is ~2/P of whole (P/2 speedup once P > 2), and the held CSR is
    ~1/P — the ≤60% RSS acceptance bar of the ingest bench row follows
    at P=2 for any dataset whose CSR dominates the index."""
    # big file so the KV exchange term is negligible in the ratio
    whole = perf.ingest_model(8e9, 1_000_000, 75_000_000, 8,
                              mode="whole", d=47_236)
    stream = perf.ingest_model(8e9, 1_000_000, 75_000_000, 8,
                               mode="stream", d=47_236)
    assert stream["bytes_read"] == pytest.approx(
        whole["bytes_read"] / 4)                   # 2/P at P=8
    assert stream["csr_peak_bytes"] < 0.2 * whole["csr_peak_bytes"]
    with pytest.raises(ValueError, match="whole|stream"):
        perf.ingest_model(1e6, 10, 100, 2, mode="mmap", d=10)
