"""CI serving smoke: train a small model, serve it over TCP, inject a
fresh checkpoint generation under traffic, assert the hot-swap fires
and the answers change — events schema-validated, serve gauges grepped.

Not a pytest file (no ``test_`` prefix): run it directly —

    PYTHONPATH=. python tests/serve_smoke.py <artifact-dir>

It drives the REAL CLI twice: once to train (CoCoA+ on the committed
small_train.dat, checkpoints into a shared directory) and once with
``--serve`` (the production scoring loop: compiled bucket scorer,
adaptive micro-batcher, hot-swap watcher), then talks to the server
over a plain socket exactly like a client would.  The injected
generation is written through ``cocoa_tpu.checkpoint`` — the same
atomic-rename + validation path the trainer uses — so the swap the
smoke observes is the production swap.  Exit code 0 = every check held.
The same mechanics are pinned as tests (tests/test_serving.py); this
script keeps the end-to-end CLI path visible as its own CI signal with
uploadable artifacts.
"""

import json
import os
import socket
import subprocess
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
D = 9947


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    outdir = argv[0] if argv else tempfile.mkdtemp(prefix="serve-smoke-")
    os.makedirs(outdir, exist_ok=True)
    ck = os.path.join(outdir, "ck")
    events_path = os.path.join(outdir, "serve-events.jsonl")
    metrics_path = os.path.join(outdir, "serve-metrics.prom")
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    failures = []

    print("serve-smoke: training the model (CoCoA+, 40 rounds, "
          "checkpoints every 20)", flush=True)
    rc = subprocess.run(
        [sys.executable, "-m", "cocoa_tpu.cli",
         "--trainFile=data/small_train.dat", f"--numFeatures={D}",
         "--numSplits=4", "--numRounds=40", "--debugIter=10",
         "--chkptIter=20", f"--chkptDir={ck}", "--localIterFrac=0.1",
         "--lambda=0.001", "--layout=dense", "--math=fast",
         "--gapTarget=1e-4", "--justCoCoA=true", "--quiet"],
        cwd=ROOT, env=env, timeout=600).returncode
    if rc != 0:
        print(f"serve-smoke FAIL: training exited {rc}")
        return 1

    for serve_dtype in (None, "bf16"):
        failures += serve_phase(ck, outdir, env, serve_dtype)
    if failures:
        for msg in failures:
            print(f"serve-smoke FAIL: {msg}")
        return 1
    print(f"serve-smoke: OK — trained, served (f32 + bf16 variants), "
          f"hot-swapped, schema valid, gauges present "
          f"(artifacts in {outdir})")
    return 0


def serve_phase(ck: str, outdir: str, env: dict,
                serve_dtype=None) -> list:
    """One full serve/score/inject/swap/shutdown cycle against the real
    CLI; ``serve_dtype`` None runs the canonical f32 path, "bf16" the
    low-precision variant (same checks, plus the model_quantize event
    stream, the certificate gauges, and the per-answer dtype field).
    Returns the failure strings (empty = the phase held)."""
    tag = serve_dtype or "f32"
    events_path = os.path.join(outdir, f"serve-events-{tag}.jsonl")
    metrics_path = os.path.join(outdir, f"serve-metrics-{tag}.prom")
    failures = []
    flags = [sys.executable, "-m", "cocoa_tpu.cli", "--serve=0",
             f"--chkptDir={ck}", f"--numFeatures={D}",
             "--serveBatch=8,64", "--serveSlaMs=50",
             f"--events={events_path}", f"--metrics={metrics_path}"]
    if serve_dtype:
        flags.append(f"--serveDtype={serve_dtype}")
    print(f"serve-smoke: starting the {tag} server (--serve=0, "
          f"buckets 8/64)", flush=True)
    server = subprocess.Popen(flags, cwd=ROOT, env=env,
                              stdout=subprocess.PIPE, text=True)
    try:
        port = None
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            line = server.stdout.readline()
            if not line:
                break
            print(f"serve-smoke: server: {line.rstrip()}", flush=True)
            if "listening on" in line:
                port = int(line.split("listening on ")[1]
                           .split()[0].rsplit(":", 1)[1])
                break
        if port is None:
            return [f"{tag} server never announced its port"]

        s = socket.create_connection(("127.0.0.1", port), timeout=30)
        f = s.makefile("rwb")

        def score_batch():
            f.write(b"3:1.0;5:2.5 7:-1.0;10:0.5\n")
            f.flush()
            return json.loads(f.readline())

        first = score_batch()
        if not (isinstance(first, list) and len(first) == 3
                and all("margin" in r for r in first)):
            failures.append(f"bad batch response: {first}")
        # every answer declares the model form that produced it — the
        # client-visible face of the certificate (bf16 when certified,
        # f32 after a fallback publish; the plain server always f32)
        want_dtypes = {"f32"} if serve_dtype is None \
            else {serve_dtype, "f32"}
        if not all(r.get("dtype") in want_dtypes for r in first):
            failures.append(
                f"answers carry dtype "
                f"{[r.get('dtype') for r in first]}, expected one of "
                f"{sorted(want_dtypes)}")
        r0 = first[0].get("round") if first else None
        print(f"serve-smoke: scored a 3-query batch on model r{r0}",
              flush=True)

        # inject a NEW checkpoint generation through the production
        # writer (atomic rename + validated read on the server side):
        # same shape, deliberately different values -> answers change
        from cocoa_tpu import checkpoint as ckpt_lib

        meta, w, _ = ckpt_lib.load(ckpt_lib.latest(ck, "CoCoA+"))
        new_round = int(meta["round"]) + 10
        ckpt_lib.save(ck, "CoCoA+", new_round,
                      np.asarray(w) * 0.5, None, gap=1e-5)
        print(f"serve-smoke: injected generation r{new_round}",
              flush=True)

        swapped = None
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            resp = score_batch()
            if resp and resp[0].get("round") == new_round:
                swapped = resp
                break
            time.sleep(0.1)
        if swapped is None:
            failures.append("the server never served the injected "
                            "generation (no hot-swap observed)")
        else:
            # bf16(0.5*w) == 0.5*bf16(w) exactly, but the certificate
            # may legitimately decide differently across publishes
            # (the calibration ring grows with real traffic), so the
            # quantized phase allows one bound's worth of slack between
            # the quantized and f32 forms
            tol = 1e-4 if serve_dtype is None else 2e-2
            for old, new in zip(first, swapped):
                if "margin" not in old or "margin" not in new:
                    continue
                want = old["margin"] * 0.5
                if abs(new["margin"] - want) > tol + abs(want) * tol:
                    failures.append(
                        f"post-swap margin {new['margin']} != half the "
                        f"pre-swap {old['margin']} — the swap did not "
                        f"serve the injected w")
            print(f"serve-smoke: hot-swap observed at r{new_round}, "
                  f"answers changed as injected", flush=True)

        f.write(b"shutdown\n")
        f.flush()
        ack = json.loads(f.readline())
        if ack.get("ok") != "shutting down":
            failures.append(f"bad shutdown ack: {ack}")
        s.close()
        rc = server.wait(timeout=60)
        if rc != 0:
            failures.append(f"server exited {rc} after shutdown")
    finally:
        if server.poll() is None:
            server.kill()
            server.wait(timeout=10)

    from cocoa_tpu.telemetry import schema as tele_schema

    errs = tele_schema.check_file(events_path)
    if errs:
        failures.append(f"{tag} events schema violations: {errs[:5]}")
    recs = [json.loads(ln) for ln in open(events_path)]
    swaps = [r for r in recs if r["event"] == "model_swap"]
    if not any(r.get("round", -1) == new_round for r in swaps):
        failures.append(f"no model_swap event for the injected "
                        f"generation r{new_round} in the {tag} stream")
    if not any(r["event"] == "serve_request" for r in recs):
        failures.append(f"no serve_request events in the {tag} stream")
    needles = ["cocoa_serve_qps", "cocoa_serve_requests_total",
               "cocoa_serve_latency_seconds_count",
               "cocoa_serve_batch_fill_ratio",
               "cocoa_model_swaps_total",
               "cocoa_model_gap_age_seconds"]
    if serve_dtype:
        # the quantize stream: one model_quantize per publish (initial
        # load + the injected swap), and the certificate families
        quant = [r for r in recs if r["event"] == "model_quantize"]
        if len(quant) < 2:
            failures.append(
                f"expected a model_quantize event per publish in the "
                f"{tag} stream, got {len(quant)}")
        elif not all(r["serve_dtype"] == serve_dtype
                     and r["served"] in (serve_dtype, "f32")
                     and r["calib_n"] > 0 and r["bound"] is not None
                     for r in quant):
            failures.append(f"malformed model_quantize events: "
                            f"{quant[:2]}")
        needles += ["cocoa_serve_margin_error_bound",
                    "cocoa_serve_dtype_fallbacks_total"]
    metrics_text = open(metrics_path).read()
    for needle in needles:
        if needle not in metrics_text:
            failures.append(f"{needle} missing from the {tag} metrics "
                            f"textfile")
    if not failures:
        print(f"serve-smoke: {tag} phase OK — served, hot-swapped, "
              f"{len(swaps)} swap event(s), schema valid, gauges "
              f"present", flush=True)
    return failures


if __name__ == "__main__":
    sys.exit(main())
