"""Round-6 satellite fixes (ADVICE.md round 5).

- data/sharding.py: multi-process + multiplexed dp mesh (D != K) must be
  an explicit error, not a silent fall-through to the replicated builder.
  (Round 13 lifts this for divisible K — the distributed builder stacks
  m = K/D shards per device; only a NON-divisor K stays a loud error.)
- solvers/base.py: the divergence guard is a resolvable flag
  (--divergenceGuard=auto|on|off; auto arms only below the safe K·γ σ′).
- solvers/base.py drive_on_device: a stall-guard fire on the FINAL chunk
  must still classify ``traj.stopped`` (the old n_done<n_chunks inference
  missed it).
- solvers/cocoa.py sigma=auto cleanup: only THIS run's checkpoint files
  (exact algorithm prefix, trial round range) are deleted after a
  diverged trial.
- cli.py: inferred meshes that leave devices idle print a note.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cocoa_tpu.config import DebugParams, Params
from cocoa_tpu.data.libsvm import LibsvmData
from cocoa_tpu.data.sharding import shard_dataset
from cocoa_tpu.parallel import make_mesh
from cocoa_tpu.solvers import base, run_cocoa


def _dense_data(n=48, d=16, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.standard_normal((n, d))
    X /= np.linalg.norm(X, axis=1, keepdims=True)
    y = np.where(X @ rng.standard_normal(d) >= 0, 1.0, -1.0)
    indptr = np.arange(0, (n + 1) * d, d, dtype=np.int64)
    return LibsvmData(labels=y, indptr=indptr,
                      indices=np.tile(np.arange(d, dtype=np.int32), n),
                      values=X.reshape(-1), num_features=d)


# --- data/sharding.py: multi-process multiplexed-mesh guard ---------------


def test_multiprocess_multiplexed_mesh_accepted(monkeypatch):
    """Round 13 lifts the round-6 rejection: a multi-process multiplexed
    dp mesh (K divisible by D) routes through the distributed builder —
    with every device addressable it must reproduce the replicated
    control bit-for-bit; a non-divisor K stays a loud error."""
    data = _dense_data()
    mesh = make_mesh(2)
    ctrl = shard_dataset(data, k=4, layout="dense", dtype=jnp.float32,
                         mesh=mesh)
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    with pytest.raises(ValueError, match="divisible by the dp mesh"):
        shard_dataset(data, k=3, layout="dense", dtype=jnp.float32,
                      mesh=mesh)
    ds = shard_dataset(data, k=4, layout="dense", dtype=jnp.float32,
                       mesh=mesh)
    assert ds.k == 4
    for field, want in ctrl.shard_arrays().items():
        np.testing.assert_array_equal(np.asarray(ds.shard_arrays()[field]),
                                      np.asarray(want), err_msg=field)


def test_singleprocess_multiplexed_mesh_still_works():
    data = _dense_data()
    mesh = make_mesh(2)
    ds = shard_dataset(data, k=4, layout="dense", dtype=jnp.float32,
                       mesh=mesh)
    assert ds.k == 4  # 2 logical shards multiplex per device, as before


# --- solvers/base.py: the divergence guard flag ---------------------------


def test_resolve_divergence_guard():
    r = base.resolve_divergence_guard
    assert r("on", "cocoa", 4.0, 4, 1.0) is True
    assert r("off", "plus", 1.0, 4, 1.0) is False
    # auto: armed only for σ′ overridden below the safe K·γ bound, and
    # only for modes whose subproblem reads σ′
    assert r("auto", "plus", 2.0, 4, 1.0) is True      # σ′ < K·γ
    assert r("auto", "plus", 4.0, 4, 1.0) is False     # the safe default
    assert r("auto", "cocoa", 1.0, 4, 1.0) is False    # σ unused
    assert r("auto", "frozen", 1.0, 4, 1.0) is False
    assert r("auto", "prox", 1.0, 4, 1.0) is True
    with pytest.raises(ValueError, match="auto|on|off"):
        r("maybe", "plus", 1.0, 4, 1.0)


def test_drive_guard_off_runs_full_budget(monkeypatch):
    """A stalling gap-targeted run completes its round budget when the
    guard is disarmed (and bails out when armed) — host driver."""
    monkeypatch.setattr(base, "STALL_EVALS", 3)
    monkeypatch.setattr(base, "STALL_ROUNDS", 3)
    params = Params(n=8, num_rounds=20, local_iters=1)
    debug = DebugParams(debug_iter=1, seed=0)

    def run(guard):
        state = (jnp.zeros(4),)
        traj = base.drive(
            "t", params, debug, state, lambda t, s: s,
            lambda s: (1.0, 1.0, None),   # constant gap: pure stall
            quiet=True, gap_target=1e-6, divergence_guard=guard,
        )[1]
        return traj

    armed = run(True)
    assert armed.stopped == "diverged"
    assert armed.records[-1].round < 20
    off = run(False)
    assert off.stopped is None
    assert off.records[-1].round == 20


def test_safe_sigma_auto_guard_unarmed(monkeypatch):
    """End-to-end: with --divergenceGuard=auto (default) a SAFE-σ′ run is
    never labeled DIVERGED even when its gap stalls — the ADVICE r5
    mislabel; forcing --divergenceGuard=on restores the old behavior."""
    monkeypatch.setattr(base, "STALL_EVALS", 3)
    monkeypatch.setattr(base, "STALL_ROUNDS", 3)
    data = _dense_data(n=32, d=8, seed=1)
    ds = shard_dataset(data, k=4, layout="dense", dtype=jnp.float64)
    # H=1: one coordinate step per shard per round — the gap improves a
    # sliver per eval, far under 25% per 3-eval window (slow, NOT diverging)
    params = Params(n=data.n, num_rounds=12, local_iters=1, lam=0.01)
    debug = DebugParams(debug_iter=1, seed=0)
    kw = dict(plus=True, quiet=True, gap_target=1e-12, rng="jax")
    _, _, traj = run_cocoa(ds, params, debug, **kw)   # σ′ = K·γ (safe)
    assert traj.stopped != "diverged"
    assert traj.records[-1].round == 12
    _, _, traj_on = run_cocoa(ds, params, debug, divergence_guard="on",
                              **kw)
    assert traj_on.stopped == "diverged"


def test_sigma_auto_rejects_guard_off():
    data = _dense_data()
    ds = shard_dataset(data, k=4, layout="dense", dtype=jnp.float64)
    params = Params(n=data.n, num_rounds=4, local_iters=2, sigma="auto")
    with pytest.raises(ValueError, match="divergence guard"):
        run_cocoa(ds, params, DebugParams(debug_iter=2, seed=0), plus=True,
                  quiet=True, gap_target=1e-3, divergence_guard="off")


# --- solvers/base.py: drive_on_device final-chunk classification ----------


def _device_run(gaps, gap_target, stall_evals, divergence_guard=True):
    """Drive a toy device loop through `gaps` (one eval per chunk)."""
    gaps = jnp.asarray(gaps, jnp.float32)

    def chunk_kernel(state, chunk, shard_arrays):
        (i,) = state
        return (i + 1.0,)

    def eval_kernel(state, shard_arrays, test_arrays):
        (i,) = state
        g = gaps[jnp.int32(i) - 1]
        return jnp.stack([g, g, jnp.nan])

    idxs_all = jnp.zeros((len(gaps), 1, 1, 1), jnp.int32)
    state, traj = base.drive_on_device(
        "toy", (jnp.zeros((), jnp.float32),), chunk_kernel, eval_kernel,
        idxs_all, shard_arrays=jnp.zeros(()), quiet=True,
        gap_target=gap_target, stall_evals=stall_evals,
        divergence_guard=divergence_guard,
    )
    return traj


def test_device_loop_stall_on_final_chunk_classified():
    """The stall window trips exactly on the LAST chunk: the old
    0 < n_done < n_chunks inference saw a 'completed' run; the device-side
    flags classify it DIVERGED (ADVICE r5)."""
    traj = _device_run([1.0, 1.0, 1.0], gap_target=1e-6, stall_evals=2)
    assert len(traj.records) == 3
    assert traj.stopped == "diverged"


def test_device_loop_target_on_final_chunk_classified():
    traj = _device_run([1.0, 1.0, 1e-7], gap_target=1e-6, stall_evals=2)
    assert traj.stopped == "target"


def test_device_loop_guard_off_completes():
    traj = _device_run([1.0, 1.0, 1.0, 1.0], gap_target=1e-6,
                       stall_evals=2, divergence_guard=False)
    assert traj.stopped is None
    assert len(traj.records) == 4


def test_device_loop_full_budget_unclassified():
    """A run that simply exhausts its chunks (converging, target not yet
    reached) stays stopped=None exactly as before."""
    traj = _device_run([1.0, 0.5, 0.25], gap_target=1e-6, stall_evals=12)
    assert traj.stopped is None
    assert len(traj.records) == 3


# --- solvers/cocoa.py: sigma=auto checkpoint cleanup scoping --------------


def test_sigma_auto_cleanup_scoped_to_trial(tmp_path, monkeypatch, capsys):
    """After a diverged trial, only the TRIAL's checkpoints (exact
    'CoCoA+-r' prefix, rounds ≤ the diverged round) are removed — a
    concurrent plain-CoCoA run's files and higher-round CoCoA+ files in
    the same directory survive (ADVICE r5: the bare 'CoCoA' prefix
    deleted them all).  Pinned on the --sigmaSchedule=trial A/B control —
    the in-loop anneal default never restarts, so it has no checkpoints
    to clean up (tests/test_sigma_anneal.py)."""
    from cocoa_tpu.solvers import cocoa as cocoa_mod
    from cocoa_tpu.utils.logging import RoundRecord, Trajectory

    data = _dense_data()
    ds = shard_dataset(data, k=4, layout="dense", dtype=jnp.float64)
    trial_sigma = 4 / 2.0
    real = cocoa_mod.run_sdca_family

    def spy(ds_, params_, debug_, name_, alg, **kw):
        if alg[2] == trial_sigma:
            # the trial "wrote" checkpoints up to its diverged round; a
            # concurrent run's files appear in the same window
            (tmp_path / "CoCoA+-r000392.npz").write_bytes(b"x")
            (tmp_path / "CoCoA+-r000392.npz.json").write_text("{}")
            (tmp_path / "CoCoA-r000100.npz").write_bytes(b"x")     # CoCoA run
            (tmp_path / "CoCoA+-r000999.npz").write_bytes(b"x")    # later run
            t = Trajectory(name_, quiet=True)
            t.records.append(RoundRecord(round=392, wall_time=None, gap=5.0))
            t.stopped = "diverged"
            return None, None, t
        return real(ds_, params_, debug_, name_, alg, **kw)

    monkeypatch.setattr(cocoa_mod, "run_sdca_family", spy)
    params = Params(n=data.n, num_rounds=6, local_iters=2, lam=0.01,
                    sigma="auto")
    debug = DebugParams(debug_iter=2, seed=0, chkpt_iter=100,
                        chkpt_dir=str(tmp_path))
    run_cocoa(ds, params, debug, plus=True, quiet=False, math="fast",
              gap_target=1e-3, rng="jax", sigma_schedule="trial")
    names = sorted(p.name for p in tmp_path.iterdir())
    assert "CoCoA+-r000392.npz" not in names          # trial ckpt deleted
    assert "CoCoA+-r000392.npz.json" not in names     # and its sidecar
    assert "CoCoA-r000100.npz" in names               # concurrent CoCoA run
    assert "CoCoA+-r000999.npz" in names              # beyond trial range
    assert "restarting with the safe" in capsys.readouterr().out


# --- cli.py: inferred-mesh idle-device note -------------------------------


def test_cli_auto_mesh_note(tmp_path, capsys):
    from cocoa_tpu import cli
    from cocoa_tpu.data.synth import synth_dense, write_libsvm

    path = str(tmp_path / "train.dat")
    write_libsvm(synth_dense(48, 12, seed=0), path)
    # prime numSplits=11 on 8 devices: the largest fitting divisor is 1 —
    # all shards on one chip, 7 devices idle (the worst-case cliff)
    rc = cli.main([
        f"--trainFile={path}", "--numFeatures=12", "--numSplits=11",
        "--numRounds=2", "--localIterFrac=0.25", "--lambda=.01",
        "--justCoCoA=true", "--debugIter=2", "--rng=jax",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "note: inferred mesh uses 1 of 8 devices" in out
    assert "numSplits divisible by 8" in out

    # an explicit --mesh choice is the user's own: no note
    rc = cli.main([
        f"--trainFile={path}", "--numFeatures=12", "--numSplits=11",
        "--numRounds=2", "--localIterFrac=0.25", "--lambda=.01",
        "--justCoCoA=true", "--debugIter=2", "--rng=jax", "--mesh=1",
    ])
    assert rc == 0
    assert "note: inferred mesh" not in capsys.readouterr().out


def test_cli_divergence_guard_flag(tmp_path, capsys):
    from cocoa_tpu import cli
    from cocoa_tpu.data.synth import synth_dense, write_libsvm

    path = str(tmp_path / "train.dat")
    write_libsvm(synth_dense(24, 8, seed=0), path)
    rc = cli.main([f"--trainFile={path}", "--numFeatures=8",
                   "--divergenceGuard=maybe"])
    assert rc == 2
    assert "auto|on|off" in capsys.readouterr().err

    rc = cli.main([f"--trainFile={path}", "--numFeatures=8",
                   "--sigma=auto", "--gapTarget=1e-3",
                   "--divergenceGuard=off"])
    assert rc == 2
    assert "divergence guard" in capsys.readouterr().err
