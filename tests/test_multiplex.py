"""Shard-multiplexed mesh path: K logical shards on D < K devices.

Spark multiplexes K partitions onto fewer executors (``coalesce``,
OptUtils.scala:14: the partition count is a data property, not the worker
count).  The mesh analogue (VERDICT r4 item 7): K = m·D shards ride a
D-device dp mesh with m shards stacked per device — the shard_map body runs
its local (m, ...) block exactly like the single-chip path (inner vmap, or
the batched Pallas/block kernels) and folds the in-device shard sum into
the same ONE psum per round.  These tests pin the multiplexed trajectories
to the single-chip K-shard trajectories bit-close, across driver paths.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from cocoa_tpu.config import DebugParams, Params
from cocoa_tpu.data import shard_dataset
from cocoa_tpu.evals import objectives
from cocoa_tpu.parallel import make_mesh
from cocoa_tpu.parallel.fanout import shards_per_device
from cocoa_tpu.solvers import run_cocoa, run_sgd

K, D = 8, 4   # 2 logical shards per device


def _params(data, num_rounds=6):
    return Params(n=data.n, num_rounds=num_rounds, local_iters=8, lam=0.01)


def _debug():
    return DebugParams(debug_iter=2, seed=0)


def test_shards_per_device_validation():
    mesh = make_mesh(D)
    assert shards_per_device(mesh, D) == 1
    assert shards_per_device(mesh, K) == 2
    assert shards_per_device(None, K) == 1
    with pytest.raises(ValueError, match="multiplex"):
        shards_per_device(mesh, D + 1)


@pytest.mark.parametrize("plus", [True, False])
def test_multiplexed_mesh_equals_local(tiny_data, plus):
    """K=8 shards on a 4-device mesh == K=8 on one chip, per-round driver."""
    p = _params(tiny_data)
    mesh = make_mesh(D)
    ds_m = shard_dataset(tiny_data, k=K, layout="dense", dtype=jnp.float64,
                         mesh=mesh)
    ds_l = shard_dataset(tiny_data, k=K, layout="dense", dtype=jnp.float64)
    w_m, a_m, _ = run_cocoa(ds_m, p, _debug(), plus=plus, mesh=mesh,
                            quiet=True)
    w_l, a_l, _ = run_cocoa(ds_l, p, _debug(), plus=plus, quiet=True)
    np.testing.assert_allclose(np.asarray(w_m), np.asarray(w_l), atol=1e-12)
    np.testing.assert_allclose(np.asarray(a_m), np.asarray(a_l), atol=1e-12)


def test_multiplexed_chunked_and_device_loop(tiny_data):
    """The chunked-scan and device-resident drivers agree with the
    single-chip trajectory under multiplexing (fast math)."""
    p = _params(tiny_data)
    mesh = make_mesh(D)
    ds_m = shard_dataset(tiny_data, k=K, layout="dense", dtype=jnp.float64,
                         mesh=mesh)
    ds_l = shard_dataset(tiny_data, k=K, layout="dense", dtype=jnp.float64)
    w_l, a_l, traj_l = run_cocoa(ds_l, p, _debug(), plus=True, quiet=True,
                                 math="fast")
    w_c, a_c, _ = run_cocoa(ds_m, p, _debug(), plus=True, mesh=mesh,
                            quiet=True, math="fast", scan_chunk=3)
    np.testing.assert_allclose(np.asarray(w_c), np.asarray(w_l), atol=1e-12)
    w_d, a_d, traj_d = run_cocoa(ds_m, p, _debug(), plus=True, mesh=mesh,
                                 quiet=True, math="fast", device_loop=True)
    np.testing.assert_allclose(np.asarray(w_d), np.asarray(w_l), atol=1e-12)
    np.testing.assert_allclose(np.asarray(a_d), np.asarray(a_l), atol=1e-12)
    for rl, rd in zip(traj_l.records, traj_d.records):
        assert rl.round == rd.round
        np.testing.assert_allclose(rd.gap, rl.gap, atol=1e-12)


def test_multiplexed_sparse_layout(tiny_data):
    """The padded-CSR layout multiplexes too (no column split involved)."""
    p = _params(tiny_data, num_rounds=4)
    mesh = make_mesh(D)
    ds_m = shard_dataset(tiny_data, k=K, layout="sparse", dtype=jnp.float64,
                         mesh=mesh)
    ds_l = shard_dataset(tiny_data, k=K, layout="sparse", dtype=jnp.float64)
    w_m, _, _ = run_cocoa(ds_m, p, _debug(), plus=True, mesh=mesh, quiet=True)
    w_l, _, _ = run_cocoa(ds_l, p, _debug(), plus=True, quiet=True)
    np.testing.assert_allclose(np.asarray(w_m), np.asarray(w_l), atol=1e-12)


def test_multiplexed_block_kernel_interpret(tiny_data):
    """The batched block-chain kernel runs per-device over its m local
    shards inside shard_map (the per_round_batched multiplexed path),
    matching the single-chip block trajectory."""
    p = _params(tiny_data, num_rounds=4)
    mesh = make_mesh(D)
    ds_m = shard_dataset(tiny_data, k=K, layout="dense", dtype=jnp.float64,
                         mesh=mesh)
    ds_l = shard_dataset(tiny_data, k=K, layout="dense", dtype=jnp.float64)
    kw = dict(plus=True, quiet=True, math="fast", block_size=8,
              scan_chunk=2)
    w_m, a_m, _ = run_cocoa(ds_m, p, _debug(), mesh=mesh, **kw)
    w_l, a_l, _ = run_cocoa(ds_l, p, _debug(), **kw)
    np.testing.assert_allclose(np.asarray(w_m), np.asarray(w_l), atol=1e-12)


def test_multiplexed_sgd(tiny_data):
    """The SGD family (TsSampler xs with a scalar t leaf) multiplexes."""
    p = _params(tiny_data)
    mesh = make_mesh(D)
    ds_m = shard_dataset(tiny_data, k=K, layout="dense", dtype=jnp.float64,
                         mesh=mesh)
    ds_l = shard_dataset(tiny_data, k=K, layout="dense", dtype=jnp.float64)
    for local in (True, False):
        w_m, _ = run_sgd(ds_m, p, _debug(), local=local, mesh=mesh,
                         quiet=True)
        w_l, _ = run_sgd(ds_l, p, _debug(), local=local, quiet=True)
        np.testing.assert_allclose(np.asarray(w_m), np.asarray(w_l),
                                   atol=1e-12)


def test_multiplexed_eval_matches_local(tiny_data):
    """The fused eval fanout sums partials over m local shards before its
    one psum — same objective values as the single-chip eval."""
    mesh = make_mesh(D)
    ds_m = shard_dataset(tiny_data, k=K, layout="dense", dtype=jnp.float64,
                         mesh=mesh)
    ds_l = shard_dataset(tiny_data, k=K, layout="dense", dtype=jnp.float64)
    rng = np.random.default_rng(3)
    w = rng.normal(size=ds_l.num_features)
    w_m = jnp.asarray(w)
    alpha = jnp.asarray(rng.random((K, ds_l.n_shard)))
    p_m = objectives.primal_objective(ds_m, w_m, 0.01)
    p_l = objectives.primal_objective(ds_l, jnp.asarray(w), 0.01)
    np.testing.assert_allclose(float(p_m), float(p_l), atol=1e-12)
    g_m = objectives.duality_gap(ds_m, w_m, alpha, 0.01)
    g_l = objectives.duality_gap(ds_l, jnp.asarray(w), alpha, 0.01)
    np.testing.assert_allclose(float(g_m), float(g_l), atol=1e-12)
