"""Benchmark-pipeline helpers: the slope-method timing math, the
generated-doc sync, and the real-dataset shape pin.  These produce the
recorded numbers and the claims in BASELINE.md/PARITY.md/README.md — a
silent bug here corrupts every published figure, so the pure logic is
pinned even though the suite itself only runs on hardware."""

import json
import os
import sys

import numpy as np
import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(ROOT, "benchmarks"))


def _fake_clock(monkeypatch, fixed, per_round, log):
    """Patch slope's perf_counter with a deterministic virtual clock where
    running nr rounds advances time by fixed + nr*per_round."""
    import slope as slope_mod

    class Clock:
        t = 0.0

    monkeypatch.setattr(slope_mod.time, "perf_counter", lambda: Clock.t)

    def make_run(nr):
        def run():
            Clock.t += fixed + nr * per_round
            log.append(nr)
        return run

    return make_run


def test_slope_time_cancels_fixed_cost(monkeypatch):
    from slope import slope_time

    fixed, per_round = 0.37, 0.004
    log = []
    make_run = _fake_clock(monkeypatch, fixed, per_round, log)
    sr = slope_time(make_run, 100, min_span_s=1.0, reps=2)
    np.testing.assert_allclose(sr.steady_s, 100 * per_round, rtol=1e-9)
    np.testing.assert_allclose(sr.fixed_s, fixed, rtol=1e-9)
    assert not sr.degraded and sr.span_s >= 1.0
    # no escalation needed: at m=4 the span is 300*0.004 = 1.2 >= 1.0
    assert max(log) == 400, log


def test_slope_time_escalates_when_fixed_dominates(monkeypatch):
    from slope import slope_time

    fixed, per_round = 2.0, 0.0004   # tiny workload under huge fixed cost
    log = []
    make_run = _fake_clock(monkeypatch, fixed, per_round, log)
    sr = slope_time(make_run, 100, min_span_s=1.0, reps=2)
    np.testing.assert_allclose(sr.steady_s, 100 * per_round, rtol=1e-9)
    np.testing.assert_allclose(sr.fixed_s, fixed, rtol=1e-9)
    # span at m: (m-1)*100*0.0004 >= 1.0 needs m >= 26 -> escalates to 32
    assert max(log) == 3200, log


def test_slope_time_flags_degraded_measurement(monkeypatch):
    """ADVICE r3: escalation that exits at max_mult without the span
    dominating the jitter must be flagged, not recorded silently."""
    from slope import slope_time

    log = []
    make_run = _fake_clock(monkeypatch, 2.0, 0.000001, log)
    sr = slope_time(make_run, 100, min_span_s=1.0, reps=2, max_mult=8)
    assert sr.degraded and sr.span_s < 1.0


def test_sync_doc_block_replaces_only_marked_region(tmp_path):
    import run as run_mod

    p = tmp_path / "DOC.md"
    p.write_text("head\n<!-- GENERATED:bench -->\nOLD\n"
                 "<!-- /GENERATED:bench -->\ntail\n")
    run_mod._sync_doc_block(str(p), "NEW LINE\n")
    assert p.read_text() == ("head\n<!-- GENERATED:bench -->\nNEW LINE\n"
                             "<!-- /GENERATED:bench -->\ntail\n")
    # marker-less file: untouched, no crash
    q = tmp_path / "PLAIN.md"
    q.write_text("nothing here\n")
    run_mod._sync_doc_block(str(q), "NEW\n")
    assert q.read_text() == "nothing here\n"


def test_generated_docs_match_recorded_results():
    """The committed BASELINE.md/PARITY.md/README.md generated blocks must
    be derivable from the committed results.jsonl — re-running the sync
    must be a no-op, or someone hand-edited a generated number."""
    import run as run_mod

    jl = os.path.join(ROOT, "benchmarks", "results.jsonl")
    if not os.path.exists(jl):
        pytest.skip("no recorded results.jsonl")
    rows = [json.loads(line) for line in open(jl)]
    rows = [r for r in rows if r.get("type") != "perf"]
    docs = ["BASELINE.md", "PARITY.md", "README.md"]
    # operate on COPIES in a temp ROOT — syncing in place would leave the
    # tracked docs rewritten if the process dies mid-test
    import shutil
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        before = {}
        for d in docs:
            shutil.copy(os.path.join(ROOT, d), os.path.join(td, d))
            before[d] = open(os.path.join(td, d)).read()
        real_root = run_mod.ROOT
        run_mod.ROOT = td
        try:
            run_mod._sync_docs(rows)
        finally:
            run_mod.ROOT = real_root
        after = {d: open(os.path.join(td, d)).read() for d in docs}
        assert before == after, [d for d in docs if before[d] != after[d]]


def test_maybe_real_rejects_wrong_shape(tmp_path):
    import run as run_mod

    p = tmp_path / "rcv1_train.binary"
    p.write_text("1 1:0.5 3:0.25\n-1 2:1.0\n")
    with pytest.raises(ValueError, match="published shape"):
        run_mod._maybe_real(str(tmp_path), "rcv1_train.binary")
    assert run_mod._maybe_real(str(tmp_path / "nope"),
                               "rcv1_train.binary") is None


# --- the CI bench-regression gate (benchmarks/check_regression.py) ----------


def test_check_regression_evaluate_logic():
    """The comparison core: certify + stay within the committed round
    bound = pass; more rounds than committed*(1+tol) or a lost
    certificate = fail with an actionable message."""
    import check_regression as cr

    gate = {"config": "demo-cocoa+", "gap_target": 1e-4,
            "rounds_tol": 0.15}
    committed = {"demo-cocoa+": {"config": "demo-cocoa+", "rounds": 440}}
    ok = {"config": "demo-cocoa+", "rounds": 440, "gap": 9e-5,
          "stopped": "target"}
    assert cr.evaluate(gate, ok, committed) == []
    # the tolerance is explicit: the bound is int(440 * 1.15) = 505
    assert cr.evaluate(gate, {**ok, "rounds": 505}, committed) == []
    fails = cr.evaluate(gate, {**ok, "rounds": 506}, committed)
    assert len(fails) == 1 and "ROUND REGRESSION" in fails[0]
    # a run that stopped on budget instead of certifying fails even at a
    # low round count
    fails = cr.evaluate(gate, {**ok, "stopped": None}, committed)
    assert fails and "no longer certifies" in fails[0]
    # no committed row -> the gate has nothing to stand on; loud fail
    assert cr.evaluate(gate, ok, {}) != []
    # a fresh run that errored out propagates the error
    assert cr.evaluate(gate, {"config": "demo-cocoa+",
                              "error": "CLI exited 2"}, committed) != []


def test_check_regression_fresh_mode(tmp_path):
    """--fresh=results.jsonl checks an existing artifact against the
    committed bounds without re-running anything."""
    import check_regression as cr

    fresh = tmp_path / "fresh.jsonl"
    # a perf-accounting row precedes the results row (both carry
    # 'config'; only the one with 'rounds' can anchor the gate)
    fresh.write_text(
        json.dumps({"config": "demo-cocoa+", "type": "perf",
                    "us_per_step": 0.1}) + "\n"
        + json.dumps(
            {"config": "demo-cocoa+", "rounds": 400, "gap": 9e-5}) + "\n")
    rc = cr.main([f"--fresh={fresh}", "--only=demo-cocoa+",
                  f"--report={tmp_path / 'rep.jsonl'}"])
    assert rc == 0
    # the report validates as the benchmarks-results dialect
    from cocoa_tpu.telemetry import schema as tele_schema

    assert tele_schema.check_file(str(tmp_path / "rep.jsonl"),
                                  kind="results") == []
    fresh.write_text(json.dumps(
        {"config": "demo-cocoa+", "rounds": 4000, "gap": 9e-5}) + "\n")
    assert cr.main([f"--fresh={fresh}", "--only=demo-cocoa+"]) == 1
    # unknown config / bad flag -> usage
    assert cr.main(["--only=nope"]) == 2
    assert cr.main(["--bogus"]) == 2
