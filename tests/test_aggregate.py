"""The fleet ops plane (cocoa_tpu/telemetry/aggregate.py): textfile
merging, the rolling SLO math, and the HTTP status endpoints.

What these tests pin:

- **exposition parsing**: ``split_sample`` never throws on a torn or
  garbage line, ``family`` folds histogram member suffixes;
- **the merge**: every sample gains a PREPENDED ``replica="<label>"``
  with existing labels kept, families group under exactly one ``# TYPE``
  line (first typed wins, untyped upgraded), and the merge is
  deterministic in sorted-label order;
- **latency accounting**: within-SLA is the cumulative bucket at the
  largest edge <= SLA — latencies in the straddling bucket count as
  over (conservative, never optimistic);
- **the SLO tracker**: injectable clock, attainment/burn from in-window
  cumulative deltas, lifetime fallback until a window holds a delta,
  and the snapshot horizon prune;
- **the HTTP plane**: /metrics, /healthz (ok vs degraded vs the
  live=null untracked source), /slo + its typed ``slo_status`` event,
  404 on unknown routes — over a real ephemeral-port server;
- renderers are also exercised directly (no sockets), because that is
  the surface the fleet smoke's curl checks stand on.
"""

import json
import os
import urllib.request

import pytest

from cocoa_tpu.telemetry import aggregate
from cocoa_tpu.telemetry import events as tele_events
from cocoa_tpu.telemetry import schema as tele_schema


@pytest.fixture(autouse=True)
def clean_bus():
    tele_events.get_bus().reset()
    yield tele_events.get_bus()
    tele_events.get_bus().reset()


# --- exposition parsing ------------------------------------------------------


def test_split_sample_shapes_and_garbage():
    assert aggregate.split_sample("cocoa_x 3") == ("cocoa_x", "", "3")
    assert aggregate.split_sample(
        'cocoa_x{tenant="0",le="0.5"} 1.5') == (
            "cocoa_x", 'tenant="0",le="0.5"', "1.5")
    for junk in ("", "   ", "# HELP cocoa_x whatever",
                 "# TYPE cocoa_x counter", "cocoa_x", "cocoa_x notnum",
                 "{oops} 3", "cocoa_x{unclosed 3"):
        assert aggregate.split_sample(junk) == (None, None, None), junk


def test_family_folds_histogram_members():
    assert aggregate.family("cocoa_round_seconds_bucket") \
        == "cocoa_round_seconds"
    assert aggregate.family("cocoa_round_seconds_sum") \
        == "cocoa_round_seconds"
    assert aggregate.family("cocoa_round_seconds_count") \
        == "cocoa_round_seconds"
    assert aggregate.family("cocoa_rounds_total") == "cocoa_rounds_total"


def test_merge_prepends_replica_and_groups_types():
    merged = aggregate.merge_expositions({
        "r1": ("# TYPE cocoa_c counter\n"
               "cocoa_c 2\n"
               'cocoa_g{tenant="1"} 7\n'),
        "r0": ("# TYPE cocoa_c counter\n"
               "cocoa_c 1\n"),
    })
    lines = merged.splitlines()
    # one TYPE line per family, sources merged in sorted-label order
    assert lines.count("# TYPE cocoa_c counter") == 1
    assert 'cocoa_c{replica="r0"} 1' in lines
    assert 'cocoa_c{replica="r1"} 2' in lines
    assert lines.index('cocoa_c{replica="r0"} 1') \
        < lines.index('cocoa_c{replica="r1"} 2')
    # existing labels survive AFTER the replica label
    assert 'cocoa_g{replica="r1",tenant="1"} 7' in lines
    # the no-TYPE family got an untyped declaration
    assert "# TYPE cocoa_g untyped" in lines


def test_merge_upgrades_untyped_family():
    # r0 (sorted first) carries the sample with no TYPE; r1 declares it
    merged = aggregate.merge_expositions({
        "r0": "cocoa_c 1\n",
        "r1": "# TYPE cocoa_c counter\ncocoa_c 2\n",
    })
    assert "# TYPE cocoa_c counter" in merged
    assert "untyped" not in merged


def test_read_sources_skips_missing(tmp_path):
    p = tmp_path / "m.prom"
    p.write_text("cocoa_c 1\n")
    out = aggregate.read_sources({"a": str(p),
                                  "b": str(tmp_path / "nope.prom")})
    assert out == {"a": "cocoa_c 1\n"}


def test_scrape_gauge_unlabeled_only():
    text = ('cocoa_model_gap_age_seconds{tenant="0"} 9\n'
            "cocoa_model_gap_age_seconds 3.5\n")
    assert aggregate.scrape_gauge(text,
                                  "cocoa_model_gap_age_seconds") == 3.5
    assert aggregate.scrape_gauge(text, "cocoa_model_round") is None


def _hist(counts_by_edge, total):
    lines = ["# TYPE cocoa_serve_latency_seconds histogram"]
    cum = 0
    for edge, n in counts_by_edge:
        cum += n
        lines.append(f'cocoa_serve_latency_seconds_bucket{{le="{edge}"}}'
                     f" {cum}")
    lines.append(f'cocoa_serve_latency_seconds_bucket{{le="+Inf"}}'
                 f" {total}")
    lines.append(f"cocoa_serve_latency_seconds_count {total}")
    return "\n".join(lines) + "\n"


def test_latency_totals_conservative_at_the_straddle():
    # 10 under 0.025s, 2 in (0.025, 0.05], 2 beyond: at sla=0.04 the
    # largest edge <= sla is 0.025, so the straddling 2 count as over
    text = _hist([("0.025", 10), ("0.05", 2)], 14)
    assert aggregate.latency_totals({"r0": text}, 0.04) == (14, 4)
    # at sla=0.05 the 0.05 bucket is within — only the tail is over
    assert aggregate.latency_totals({"r0": text}, 0.05) == (14, 2)


def test_latency_totals_sums_across_sources():
    a = _hist([("0.05", 5)], 6)
    b = _hist([("0.05", 3)], 3)
    assert aggregate.latency_totals({"r0": a, "r1": b}, 0.05) == (9, 1)


# --- the rolling SLO math ----------------------------------------------------


def test_slo_tracker_windows_burn_and_fallback():
    trk = aggregate.SloTracker(0.05, objective=0.99, fast_s=10.0,
                               slow_s=100.0)
    # empty: nothing to report
    s = trk.status(now=0.0)
    assert s["attainment"] is None and s["served_total"] == 0
    trk.observe(100, 1, now=0.0)
    # one snapshot: no window delta yet — lifetime fallback answers
    s = trk.status(now=0.0)
    assert s["attainment"] == pytest.approx(0.99)
    assert s["burn_fast"] is None and s["burn_slow"] is None
    # +5s: 100 more served, 2 more over — both windows hold the delta
    trk.observe(200, 3, now=5.0)
    s = trk.status(now=5.0)
    assert s["attainment"] == pytest.approx(0.98)
    assert s["burn_fast"] == pytest.approx(2.0)
    assert s["burn_slow"] == pytest.approx(2.0)
    assert s["served_total"] == 200 and s["over_sla_total"] == 3
    # +50s: the fast window has slid past both snapshots' delta
    trk.observe(200, 3, now=55.0)
    s = trk.status(now=55.0)
    assert s["burn_fast"] is None          # no traffic inside 10s
    assert s["burn_slow"] == pytest.approx(2.0)


def test_slo_tracker_prunes_but_keeps_a_base():
    trk = aggregate.SloTracker(0.05, slow_s=10.0)
    for t in range(0, 100, 5):
        trk.observe(t * 10, 0, now=float(t))
    # snapshots older than 2x slow_s are gone, a base survives
    assert len(trk._snaps) <= 6
    assert trk.status(now=95.0)["attainment"] == pytest.approx(1.0)


def test_slo_tracker_rejects_bad_objective():
    with pytest.raises(ValueError):
        aggregate.SloTracker(0.05, objective=1.0)


# --- the HTTP plane ----------------------------------------------------------


def _write_replica(tmp_path, name, rnd, age, hist=None):
    p = tmp_path / f"m.prom.{name}"
    text = (f"# TYPE cocoa_model_round gauge\n"
            f"cocoa_model_round {rnd}\n"
            f"# TYPE cocoa_model_gap_age_seconds gauge\n"
            f"cocoa_model_gap_age_seconds {age}\n")
    if hist:
        text += hist
    p.write_text(text)
    return str(p)


def test_renderers_healthz_ok_degraded_and_untracked(tmp_path):
    router_prom = tmp_path / "m.prom"
    router_prom.write_text("cocoa_compiles_total 0\n")
    paths = {"r0": _write_replica(tmp_path, "r0", 3, 1.5),
             "r1": _write_replica(tmp_path, "r1", 5, 0.5),
             "router": str(router_prom)}
    live = {"r0": True, "r1": True}
    plane = aggregate.StatusServer(lambda: paths, sla_s=0.05,
                                   liveness_fn=lambda: dict(live))
    h = json.loads(plane.render_healthz())
    assert h["status"] == "ok"
    assert h["round"] == 5 and h["replicas_live"] == 2
    assert h["replicas"]["r0"]["round"] == 3
    assert h["replicas"]["r0"]["gap_age_s"] == pytest.approx(1.5)
    # the router's own source is scraped but untracked: live=null
    assert h["replicas"]["router"]["live"] is None
    live["r0"] = False
    h = json.loads(plane.render_healthz())
    assert h["status"] == "degraded" and h["replicas_live"] == 1
    assert h["replicas"]["r0"]["live"] is False
    plane._http.server_close()


def test_renderers_solo_server_counts_sources_as_live(tmp_path):
    paths = {"server": _write_replica(tmp_path, "s", 2, 0.1)}
    plane = aggregate.StatusServer(lambda: paths, sla_s=0.05)
    h = json.loads(plane.render_healthz())
    assert h["status"] == "ok" and h["replicas"]["server"]["live"]
    plane._http.server_close()


def test_status_server_http_routes_and_slo_event(tmp_path, clean_bus):
    ev = tmp_path / "ev.jsonl"
    clean_bus.configure(jsonl_path=str(ev))
    hist = _hist([("0.025", 8), ("0.05", 1)], 10)
    paths = {"r0": _write_replica(tmp_path, "r0", 7, 0.2, hist=hist)}
    plane = aggregate.StatusServer(lambda: paths, sla_s=0.05,
                                   liveness_fn=lambda: {"r0": True}
                                   ).start()
    try:
        host, port = plane.address

        def get(route):
            return urllib.request.urlopen(
                f"http://{host}:{port}{route}", timeout=10)

        body = get("/metrics").read().decode()
        assert 'cocoa_model_round{replica="r0"} 7' in body
        h = json.loads(get("/healthz").read().decode())
        assert h["status"] == "ok" and h["round"] == 7
        s = json.loads(get("/slo").read().decode())
        assert s["served_total"] == 10 and s["over_sla_total"] == 1
        assert s["sla_ms"] == pytest.approx(50.0)
        assert s["replicas_live"] == 1
        with pytest.raises(urllib.error.HTTPError):
            get("/nope")
    finally:
        plane.stop()
    # the /slo evaluation landed as a schema-valid typed event
    assert not tele_schema.check_file(str(ev))
    recs = [json.loads(ln) for ln in open(ev) if ln.strip()]
    slo = [r for r in recs if r.get("event") == "slo_status"]
    assert len(slo) == 1 and slo[0]["served_total"] == 10


def test_status_server_survives_a_torn_scrape(tmp_path):
    # a sources_fn that throws must answer 500, not kill the plane
    def bad_sources():
        raise RuntimeError("torn")

    plane = aggregate.StatusServer(bad_sources, sla_s=0.05).start()
    try:
        host, port = plane.address
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://{host}:{port}/metrics", timeout=10)
        assert ei.value.code == 500
    finally:
        plane.stop()
