"""The deterministic dataset shared by tests/test_multihost.py's in-process
comparison and its subprocess workers (both import this module, so the two
sides can never desynchronize)."""

import numpy as np

N, D = 64, 24


def build_data():
    from cocoa_tpu.data.libsvm import LibsvmData

    rng = np.random.default_rng(3)
    X = rng.normal(size=(N, D)) * (rng.random(size=(N, D)) < 0.5)
    y = np.where(X @ rng.normal(size=D) > 0, 1.0, -1.0)
    indptr, indices, values = [0], [], []
    for i in range(N):
        nz = np.nonzero(X[i])[0]
        indices.append(nz.astype(np.int32))
        values.append(X[i, nz])
        indptr.append(indptr[-1] + len(nz))
    return LibsvmData(
        labels=y,
        indptr=np.asarray(indptr, np.int64),
        indices=np.concatenate(indices),
        values=np.concatenate(values),
        num_features=D,
    )
