"""The deterministic dataset shared by tests/test_multihost.py's and
tests/test_ingest.py's in-process comparisons and their subprocess workers
(all sides import this module, so they can never desynchronize)."""

import numpy as np

N, D = 64, 24


def build_data():
    from cocoa_tpu.data.libsvm import LibsvmData

    rng = np.random.default_rng(3)
    X = rng.normal(size=(N, D)) * (rng.random(size=(N, D)) < 0.5)
    y = np.where(X @ rng.normal(size=D) > 0, 1.0, -1.0)
    indptr, indices, values = [0], [], []
    for i in range(N):
        nz = np.nonzero(X[i])[0]
        indices.append(nz.astype(np.int32))
        values.append(X[i, nz])
        indptr.append(indptr[-1] + len(nz))
    return LibsvmData(
        labels=y,
        indptr=np.asarray(indptr, np.int64),
        indices=np.concatenate(indices),
        values=np.concatenate(values),
        num_features=D,
    )


def write_libsvm(path):
    """The same dataset as LIBSVM text (1-based indices, repr-precision
    values so the f64 parse round-trips bit-exactly) — the file the
    streaming-ingest harness (tests/test_ingest.py) feeds both the
    streamed workers and the whole-file control."""
    data = build_data()
    with open(path, "w") as f:
        for i in range(data.n):
            lo, hi = data.indptr[i], data.indptr[i + 1]
            pairs = " ".join(
                f"{j + 1}:{float(v)!r}"
                for j, v in zip(data.indices[lo:hi], data.values[lo:hi]))
            f.write(f"{int(data.labels[i])} {pairs}\n")
    return data
