"""Device-side execution paths (scan_chunk, device_loop) for the non-CoCoA
solvers: the chunked lax.scan and the fully device-resident lax.while_loop
must produce the same state and trajectory as the host-stepped per-round
driver, on both the single-chip and mesh paths.  (CoCoA's paths are covered
in test_fast_math.py / test_integration.py; mini-batch CD now shares
CoCoA's driver and gains the same paths.)"""

import jax.numpy as jnp
import numpy as np
import pytest

from cocoa_tpu.config import DebugParams, Params
from cocoa_tpu.data.sharding import shard_dataset
from cocoa_tpu.parallel import make_mesh
from cocoa_tpu.solvers import run_dist_gd, run_minibatch_cd, run_sgd

K = 4


def _params(tiny_data, **kw):
    defaults = dict(n=tiny_data.n, num_rounds=12, local_iters=15, lam=0.01,
                    beta=1.0, gamma=1.0)
    defaults.update(kw)
    return Params(**defaults)


_DBG = DebugParams(debug_iter=4, seed=0)


def _traj_metrics(traj):
    return [(r.round, r.primal, r.gap) for r in traj.records]


@pytest.mark.parametrize("local", [True, False])
def test_sgd_chunked_matches_per_round(tiny_data, local):
    ds = shard_dataset(tiny_data, k=K, layout="dense", dtype=jnp.float64)
    p = _params(tiny_data)
    w0, traj0 = run_sgd(ds, p, _DBG, local=local, quiet=True)
    w1, traj1 = run_sgd(ds, p, _DBG, local=local, quiet=True, scan_chunk=5)
    np.testing.assert_allclose(np.asarray(w1), np.asarray(w0), atol=1e-12)
    a, b = _traj_metrics(traj0), _traj_metrics(traj1)
    assert [x[0] for x in a] == [x[0] for x in b]
    np.testing.assert_allclose([x[1] for x in a], [x[1] for x in b],
                               atol=1e-12)


@pytest.mark.parametrize("local", [True, False])
def test_sgd_device_loop_matches_per_round(tiny_data, local):
    ds = shard_dataset(tiny_data, k=K, layout="dense", dtype=jnp.float64)
    p = _params(tiny_data)
    w0, traj0 = run_sgd(ds, p, _DBG, local=local, quiet=True)
    w1, traj1 = run_sgd(ds, p, _DBG, local=local, quiet=True,
                        device_loop=True)
    np.testing.assert_allclose(np.asarray(w1), np.asarray(w0), atol=1e-12)
    a, b = _traj_metrics(traj0), _traj_metrics(traj1)
    assert [x[0] for x in a] == [x[0] for x in b]
    np.testing.assert_allclose([x[1] for x in a], [x[1] for x in b],
                               atol=1e-12)


def test_sgd_chunked_on_mesh_matches_local(tiny_data):
    ds_l = shard_dataset(tiny_data, k=K, layout="dense", dtype=jnp.float64)
    p = _params(tiny_data)
    w0, _ = run_sgd(ds_l, p, _DBG, local=True, quiet=True)
    mesh = make_mesh(K)
    ds_m = shard_dataset(tiny_data, k=K, layout="dense", dtype=jnp.float64,
                         mesh=mesh)
    w1, _ = run_sgd(ds_m, p, _DBG, local=True, quiet=True, mesh=mesh,
                    scan_chunk=5)
    np.testing.assert_allclose(np.asarray(w1), np.asarray(w0), atol=1e-12)


def test_dist_gd_chunked_and_device_loop_match(tiny_data):
    ds = shard_dataset(tiny_data, k=K, layout="dense", dtype=jnp.float64)
    p = _params(tiny_data)
    w0, traj0 = run_dist_gd(ds, p, _DBG, quiet=True)
    w1, traj1 = run_dist_gd(ds, p, _DBG, quiet=True, scan_chunk=5)
    w2, traj2 = run_dist_gd(ds, p, _DBG, quiet=True, device_loop=True)
    np.testing.assert_allclose(np.asarray(w1), np.asarray(w0), atol=1e-12)
    np.testing.assert_allclose(np.asarray(w2), np.asarray(w0), atol=1e-12)
    for tr in (traj1, traj2):
        np.testing.assert_allclose(
            [x[1] for x in _traj_metrics(tr)],
            [x[1] for x in _traj_metrics(traj0)], atol=1e-12)


def test_dist_gd_chunked_on_mesh_matches_local(tiny_data):
    ds_l = shard_dataset(tiny_data, k=K, layout="dense", dtype=jnp.float64)
    p = _params(tiny_data)
    w0, _ = run_dist_gd(ds_l, p, _DBG, quiet=True)
    mesh = make_mesh(K)
    ds_m = shard_dataset(tiny_data, k=K, layout="dense", dtype=jnp.float64,
                         mesh=mesh)
    w1, _ = run_dist_gd(ds_m, p, _DBG, quiet=True, mesh=mesh, scan_chunk=4)
    np.testing.assert_allclose(np.asarray(w1), np.asarray(w0), atol=1e-12)


@pytest.mark.slow
@pytest.mark.parametrize("layout", ["dense", "sparse"])
def test_mbcd_device_paths_match(tiny_data, layout):
    """Mini-batch CD through the shared SDCA driver: chunked, device-loop,
    and Pallas (interpret) paths all track the per-round exact path."""
    ds = shard_dataset(tiny_data, k=K, layout=layout, dtype=jnp.float64)
    p = _params(tiny_data)
    w0, a0, _ = run_minibatch_cd(ds, p, _DBG, quiet=True)
    w1, a1, _ = run_minibatch_cd(ds, p, _DBG, quiet=True, scan_chunk=5)
    w2, a2, _ = run_minibatch_cd(ds, p, _DBG, quiet=True, device_loop=True)
    np.testing.assert_allclose(np.asarray(w1), np.asarray(w0), atol=1e-12)
    np.testing.assert_allclose(np.asarray(w2), np.asarray(w0), atol=1e-12)
    np.testing.assert_allclose(np.asarray(a1), np.asarray(a0), atol=1e-12)
    np.testing.assert_allclose(np.asarray(a2), np.asarray(a0), atol=1e-12)
    # fast-math + Pallas kernel (interpret mode on CPU), frozen mode
    w3, a3, _ = run_minibatch_cd(ds, p, _DBG, quiet=True, math="fast",
                                 pallas=True, scan_chunk=5)
    np.testing.assert_allclose(np.asarray(w3), np.asarray(w0), atol=1e-9)
    np.testing.assert_allclose(np.asarray(a3), np.asarray(a0), atol=1e-9)


def test_mbcd_gap_target_early_stop(tiny_data):
    ds = shard_dataset(tiny_data, k=K, layout="dense", dtype=jnp.float64)
    p = _params(tiny_data, num_rounds=400, local_iters=30)
    dbg = DebugParams(debug_iter=20, seed=0)
    w, a, traj = run_minibatch_cd(ds, p, dbg, quiet=True, gap_target=0.5,
                                  scan_chunk=20)
    assert traj.records[-1].gap <= 0.5
    assert traj.records[-1].round < 400

def test_device_loop_records_block_timestamps(tiny_data, monkeypatch):
    """VERDICT r1 item 6: the device-resident driver stamps each
    super-block's host sync into the Trajectory, so benchmark-mode JSONL
    keeps monotone (round, time) pairs.  Rounds inside a block stay
    unobservable (wall_time=None) — only the sync boundaries are real."""
    from cocoa_tpu.solvers import base, run_cocoa

    ds = shard_dataset(tiny_data, k=K, layout="dense", dtype=jnp.float64)
    p = _params(tiny_data, num_rounds=20)
    d = DebugParams(debug_iter=2, seed=0)
    # force tiny super-blocks: each block = 1 chunk of debug_iter rounds
    monkeypatch.setattr(base, "MAX_IDX_TABLE_BYTES",
                        4 * 1 * d.debug_iter * K * p.local_iters)
    base._DEVICE_RUNS.clear()
    # sampling="host": the table-size cap (what this test shrinks to force
    # block boundaries) only governs concrete host tables — device-sampling
    # runs ship ~no table bytes and ride one block (their boundaries come
    # from chkptIter alone)
    _, _, traj = run_cocoa(ds, p, d, plus=True, quiet=True, device_loop=True,
                           sampling="host")
    base._DEVICE_RUNS.clear()
    stamps = [r.wall_time for r in traj.records if r.wall_time is not None]
    assert len(stamps) >= 2, [r.wall_time for r in traj.records]
    assert stamps == sorted(stamps)
    assert all(s > 0 for s in stamps)
    # every block boundary (here: every chunk) is stamped
    assert traj.records[-1].wall_time is not None


def test_device_loop_ckpt_round_matches_early_stop(tiny_data, tmp_path):
    """A gap-target run can stop the device while_loop mid-super-block;
    the checkpoint saved at that block's boundary must carry the round
    the run ACTUALLY executed (one eval record per executed chunk), not
    the nominal block end — a later --resume would otherwise skip rounds
    the round-keyed sampler never ran."""
    from cocoa_tpu import checkpoint as ckpt_lib
    from cocoa_tpu.solvers import run_cocoa

    ds = shard_dataset(tiny_data, k=K, layout="dense", dtype=jnp.float64)
    p = _params(tiny_data, num_rounds=60, local_iters=25, lam=0.001)
    # debug_iter=2, chkpt_iter=10 -> blocks of 5 chunks (10 rounds); a
    # loose gap target stops well before round 60, usually mid-block
    dbg = DebugParams(debug_iter=2, seed=0, chkpt_iter=10,
                      chkpt_dir=str(tmp_path))
    w, a, traj = run_cocoa(ds, p, dbg, plus=True, quiet=True,
                           device_loop=True, gap_target=0.15)
    last_round = traj.records[-1].round
    assert traj.records[-1].gap <= 0.15
    assert last_round < 60, "target must hit before the round cap"
    path = ckpt_lib.latest(str(tmp_path), "CoCoA+")
    assert path is not None, "device loop saved no checkpoint"
    meta, _w, _a = ckpt_lib.load(path)
    assert meta["round"] <= last_round, (
        f"checkpoint round {meta['round']} overstates executed "
        f"round {last_round}"
    )
