"""The in-loop telemetry subsystem (cocoa_tpu/telemetry/).

What these tests pin:

- **event ordering + JSONL schema** on both the host-chunked and the
  device-resident drive* paths — every run leaves a seq-ordered typed
  stream that cocoa_tpu/telemetry/schema.py accepts;
- **io_callback-path vs fetch-fallback parity**: the live device stream
  (ordered io_callback inside the lax.while_loop) and the end-of-run
  fetch replay emit the SAME events with the SAME values — they decode
  the same f32 buffer through the same DeviceTap;
- **soundness**: enabling telemetry leaves the final ``(w, alpha)`` AND
  the σ′-schedule sched leaf bit-identical to a telemetry-off run (the
  bridge is side-effect-only: nothing in the loop carry reads it);
- the satellites: trajectory dumps carry a manifest header and the
  ``stopped`` reason; ``--quiet`` divergence still emits a
  machine-readable event; the metrics textfile counters; the schema
  checker accepts benchmarks/results.jsonl and rejects malformed streams.
"""

import json
import os

import numpy as np
import pytest

import jax.numpy as jnp

from cocoa_tpu import checkpoint as ckpt_lib
from cocoa_tpu.config import DebugParams, Params
from cocoa_tpu.solvers import run_cocoa
from cocoa_tpu.telemetry import events as tele_events
from cocoa_tpu.telemetry import schema as tele_schema
from cocoa_tpu.telemetry.metrics import MetricsWriter
from cocoa_tpu.utils.logging import Trajectory
from test_divergence import _coherent_dataset

K, LAM = 4, 1e-4


@pytest.fixture(autouse=True)
def clean_bus():
    """Every test starts and ends with an inert bus (the process-global
    singleton must not leak sinks between tests)."""
    tele_events.get_bus().reset()
    yield tele_events.get_bus()
    tele_events.get_bus().reset()


def _collect():
    events = []
    tele_events.get_bus().subscribe(events.append)
    return events


def _backoff_run(device_loop, **kw):
    """The forced-backoff config (test_sigma_anneal's fixture): σ′ start
    1.0 = K·γ/4 on adversarially coherent shards, cadence 25 — the anneal
    schedule MUST back off in-loop before certifying."""
    ds, n = _coherent_dataset(k=K)
    params = Params(n=n, num_rounds=1600, local_iters=16, lam=LAM,
                    sigma=1.0)
    debug = kw.pop("debug", None) or DebugParams(debug_iter=25, seed=0)
    return run_cocoa(ds, params, debug, plus=True, quiet=True, math="fast",
                     device_loop=device_loop, gap_target=1e-3, rng="jax",
                     sigma_schedule="anneal", **kw)


def _strip(events, drop=("ts", "seq")):
    """Comparable view of an event stream: timing fields dropped, and the
    sanitizer's transport-bookkeeping events (``host_transfer``/
    ``compile``, analysis/sanitize.py) filtered out — their position is
    inherently path-dependent (the live stream emits evals BEFORE the
    end-of-run fetch; the fetch-replay bridge emits them after), while
    the parity contract here is about the decoded eval/backoff events."""
    return [{k: v for k, v in e.items() if k not in drop}
            for e in events
            if e.get("event") not in ("host_transfer", "compile")]


# --- the acceptance pin -----------------------------------------------------


def test_device_stream_matches_fetched_trajectory_bitforbit():
    """A --sigmaSchedule=anneal forced-backoff run on the device-resident
    path emits ordered round_eval and sigma_backoff events DURING the run
    (io_callback path) whose values match the end-of-run fetched
    trajectory bit-for-bit."""
    assert tele_events.io_callback_supported(), \
        "this jax must support the ordered io_callback bridge"
    events = _collect()
    w, alpha, traj = _backoff_run(device_loop=True)
    assert traj.stopped == "target"

    evals = [e for e in events if e["event"] == "round_eval"]
    backoffs = [e for e in events if e["event"] == "sigma_backoff"]
    assert len(evals) == len(traj.records)
    for e, r in zip(evals, traj.records):
        assert e["t"] == r.round
        assert e["primal"] == r.primal      # bit-for-bit: same f32 buffer
        assert e["gap"] == r.gap
        assert e["sigma"] == r.sigma
    # the schedule was FORCED to back off, and each backoff event lands
    # exactly where consecutive records change σ′
    assert len(backoffs) >= 1
    rec_transitions = [
        (b.round, a.sigma, b.sigma)
        for a, b in zip(traj.records, traj.records[1:]) if a.sigma != b.sigma
    ]
    assert [(e["t"], e["from_sigma"], e["sigma"]) for e in backoffs] \
        == rec_transitions
    # ordered: seq strictly increasing, and each backoff follows the
    # round_eval that triggered it
    seqs = [e["seq"] for e in events]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
    for b in backoffs:
        trigger = [e for e in evals if e["t"] == b["t"]]
        assert trigger and trigger[0]["seq"] < b["seq"]


def test_io_callback_path_vs_fetch_fallback_parity(monkeypatch):
    """Forcing the fetch-fallback bridge (io_callback 'unavailable') must
    produce the same events with the same values — and the same final
    state — as the live stream."""
    streamed = _collect()
    w1, a1, t1 = _backoff_run(device_loop=True)
    tele_events.get_bus().reset()

    monkeypatch.setattr(tele_events, "io_callback_supported", lambda: False)
    replayed = _collect()
    w2, a2, t2 = _backoff_run(device_loop=True)

    assert _strip(streamed) == _strip(replayed)
    np.testing.assert_array_equal(np.asarray(w1), np.asarray(w2))
    np.testing.assert_array_equal(np.asarray(a1), np.asarray(a2))


def test_telemetry_on_vs_off_state_bit_identical(tmp_path):
    """Telemetry must be side-effect-only: (w, alpha) and the sched leaf
    (via the checkpoints, which carry it) are bit-identical with the bus
    active vs inert."""
    debug_on = DebugParams(debug_iter=25, seed=0, chkpt_iter=100,
                           chkpt_dir=str(tmp_path / "on"))
    debug_off = DebugParams(debug_iter=25, seed=0, chkpt_iter=100,
                            chkpt_dir=str(tmp_path / "off"))
    tele_events.get_bus().configure(
        jsonl_path=str(tmp_path / "events.jsonl"))
    w1, a1, t1 = _backoff_run(device_loop=True, debug=debug_on)
    tele_events.get_bus().reset()
    w2, a2, t2 = _backoff_run(device_loop=True, debug=debug_off)

    np.testing.assert_array_equal(np.asarray(w1), np.asarray(w2))
    np.testing.assert_array_equal(np.asarray(a1), np.asarray(a2))
    names = sorted(os.listdir(tmp_path / "on"))
    assert names == sorted(os.listdir(tmp_path / "off"))
    for name in names:
        if not name.endswith(".npz"):
            continue
        m1, _, _ = ckpt_lib.load(str(tmp_path / "on" / name))
        m2, _, _ = ckpt_lib.load(str(tmp_path / "off" / name))
        assert m1["sched"] == m2["sched"], name   # the sched leaf, exact


# --- host-chunked vs device-resident event streams --------------------------


def test_host_and_device_paths_emit_identical_streams():
    """The host-chunked twin makes identical schedule decisions
    (sched_host_step is the device watch's bit-twin), so the two paths'
    event streams must agree on every value the math determines."""
    ev_host = _collect()
    _backoff_run(device_loop=False)
    tele_events.get_bus().reset()
    ev_dev = _collect()
    _backoff_run(device_loop=True)

    keep = ("event", "algorithm", "t", "primal", "gap", "sigma",
            "sigma_stage", "stall")
    host = [{k: e.get(k) for k in keep} for e in ev_host
            if e["event"] in ("round_eval", "sigma_backoff")]
    dev = [{k: e.get(k) for k in keep} for e in ev_dev
           if e["event"] in ("round_eval", "sigma_backoff")]
    # sigma_backoff carries no stage/stall on the host path's event? it
    # does (stage) — normalize by comparing the common projection
    assert host == dev


def test_event_jsonl_schema_both_paths(tmp_path):
    for device_loop, name in ((False, "host"), (True, "dev")):
        path = str(tmp_path / f"events.{name}.jsonl")
        tele_events.get_bus().reset()
        tele_events.get_bus().configure(jsonl_path=path)
        _backoff_run(device_loop=device_loop)
        tele_events.get_bus().emit("run_end", algorithm="CoCoA+",
                                   primal=0.0, stopped="target")
        errs = tele_schema.check_file(path)
        assert errs == [], errs


# --- satellites -------------------------------------------------------------


def test_trajectory_dump_manifest_and_stopped(tmp_path):
    w, alpha, traj = _backoff_run(device_loop=True)
    traj.meta = {"dataset": "synthetic-coherent", "config_hash": "abc123"}
    path = str(tmp_path / "traj.jsonl")
    traj.dump_jsonl(path)
    lines = [json.loads(s) for s in open(path)]
    man = lines[0]["manifest"]
    assert man["algorithm"] == "CoCoA+"
    assert man["dataset"] == "synthetic-coherent"
    assert man["config_hash"] == "abc123"
    assert "jax_version" in man and "backend" in man
    assert "stopped" not in lines[-2]       # only the FINAL record
    assert lines[-1]["stopped"] == "target"
    assert tele_schema.check_file(path) == []


def test_quiet_divergence_still_leaves_event_trace(capsys):
    """--quiet silences the console DIVERGED notice but the divergence
    event must still be emitted — the machine-readable trace of the
    bail-out is the point of the bus."""
    ds, n = _coherent_dataset(k=K)
    params = Params(n=n, num_rounds=1600, local_iters=16, lam=LAM,
                    sigma=1.0)
    debug = DebugParams(debug_iter=25, seed=0)
    events = _collect()
    w, a, traj = run_cocoa(ds, params, debug, plus=True, quiet=True,
                           math="fast", gap_target=1e-3, rng="jax")
    assert traj.stopped == "diverged"
    assert "DIVERGED" not in capsys.readouterr().out
    div = [e for e in events if e["event"] == "divergence"]
    assert len(div) == 1
    assert div[0]["algorithm"] == "CoCoA+"
    assert div[0]["t"] == traj.records[-1].round
    assert div[0]["n_evals"] >= 12


def test_checkpoint_write_events(tmp_path):
    events = _collect()
    debug = DebugParams(debug_iter=25, seed=0, chkpt_iter=100,
                        chkpt_dir=str(tmp_path))
    _backoff_run(device_loop=True, debug=debug)
    writes = [e for e in events if e["event"] == "checkpoint_write"]
    assert writes, "chkptIter=100 must have produced checkpoint events"
    for e in writes:
        assert e["algorithm"] == "CoCoA+"
        assert f"r{e['round']:06d}" in e["path"]
    # only the newest KEEP_GENERATIONS survive on disk (generation
    # pruning); every event still names the path it wrote at the time
    from cocoa_tpu import checkpoint as _ck

    for e in writes[-_ck.KEEP_GENERATIONS:]:
        assert os.path.exists(e["path"])
    for e in writes[:-_ck.KEEP_GENERATIONS]:
        assert not os.path.exists(e["path"])


def test_sigma_trial_restart_event(tmp_path, monkeypatch):
    """The --sigmaSchedule=trial rerun emits a typed restart event (the
    spy-diverged-trial fixture from test_divergence)."""
    from cocoa_tpu.solvers import cocoa as cocoa_mod
    from cocoa_tpu.utils.logging import RoundRecord

    ds, n = _coherent_dataset(k=K)
    real = cocoa_mod.run_sdca_family

    def spy(ds_, params_, debug_, name_, alg, **kw):
        if alg[2] == K / 2.0:
            t = Trajectory(name_, quiet=True)
            t.records.append(RoundRecord(round=392, wall_time=None, gap=5.0))
            t.stopped = "diverged"
            return None, None, t
        return real(ds_, params_, debug_, name_, alg, **kw)

    monkeypatch.setattr(cocoa_mod, "run_sdca_family", spy)
    events = _collect()
    params = Params(n=n, num_rounds=400, local_iters=16, lam=LAM,
                    sigma="auto")
    debug = DebugParams(debug_iter=4, seed=0)
    w, a, traj = run_cocoa(ds, params, debug, plus=True, quiet=True,
                           math="fast", gap_target=1e-3, rng="jax",
                           sigma_schedule="trial")
    restarts = [e for e in events if e["event"] == "restart"]
    assert len(restarts) == 1
    assert restarts[0]["reason"] == "sigma_trial_diverged"
    assert restarts[0]["sigma_trial"] == K / 2.0
    assert restarts[0]["sigma_safe"] == float(K)


def test_metrics_textfile(tmp_path):
    path = str(tmp_path / "metrics.prom")
    tele_events.get_bus().configure(metrics_path=path)
    w, alpha, traj = _backoff_run(device_loop=True)
    text = open(path).read()
    vals = {line.split(" ")[0]: line.split(" ")[1]
            for line in text.splitlines() if not line.startswith("#")}
    assert int(vals["cocoa_evals_total"]) == len(traj.records)
    # resume-safe counter: rounds advance by inter-eval deltas only (the
    # first observed eval anchors without crediting pre-resume history)
    assert int(vals["cocoa_rounds_total"]) \
        == traj.records[-1].round - traj.records[0].round
    assert int(vals["cocoa_sigma_backoffs_total"]) >= 1
    assert float(vals["cocoa_last_gap"]) == traj.records[-1].gap
    assert 'cocoa_round_seconds_bucket{le="+Inf"}' in text
    # atomic refresh convention: no temp litter left behind
    assert [f for f in os.listdir(tmp_path) if ".tmp." in f] == []


def test_round_window_profiler(monkeypatch):
    """The --profile=dir,start,stop windower starts at the first eval
    >= start and stops at the first >= stop — driven purely by the event
    stream, which is what makes it work mid-while_loop on the device
    path."""
    calls = []
    import jax

    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda d: calls.append(("start", d)))
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: calls.append(("stop",)))
    from cocoa_tpu.telemetry.profiling import RoundWindowProfiler

    prof = RoundWindowProfiler("/tmp/_win", 100, 200)
    tele_events.get_bus().subscribe(prof)
    events = _collect()
    _backoff_run(device_loop=True)
    prof.close()
    assert calls[0] == ("start", "/tmp/_win") and calls[1] == ("stop",)
    assert len(calls) == 2
    # the window triggered at the right evals (cadence 25: start at 100,
    # stop at the first eval >= 200)
    evals = [e["t"] for e in events if e["event"] == "round_eval"]
    assert 100 in evals and 200 in evals


def test_schema_checker_accepts_results_jsonl():
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(root, "benchmarks", "results.jsonl")
    assert tele_schema.check_file(path) == []


def test_schema_checker_rejects_malformed(tmp_path):
    bad = tmp_path / "bad.jsonl"
    bad.write_text(
        '{"event": "round_eval", "seq": 2, "ts": 1.0, "algorithm": "X", '
        '"t": 10, "primal": 1.0, "gap": null, "test_error": null, '
        '"sigma": null, "stall": null}\n'
        '{"event": "round_eval", "seq": 1, "ts": 1.0, "algorithm": "X", '
        '"t": 20, "primal": 1.0, "gap": null, "test_error": null, '
        '"sigma": null, "stall": null}\n'
        '{"event": "nonsense", "seq": 3, "ts": 1.0}\n')
    errs = tele_schema.check_file(str(bad))
    assert any("seq" in e for e in errs)          # order violation
    assert any("nonsense" in e for e in errs)     # unknown type
    assert tele_schema.main([str(bad)]) == 1
    # a trajectory missing its manifest header is rejected too
    traj = tmp_path / "traj.jsonl"
    traj.write_text('{"algorithm": "X", "round": 1, "wall_time": null}\n')
    assert tele_schema.check_file(str(traj), kind="trajectory") != []


def test_run_start_layout_split_schema(tmp_path):
    """The run_start manifest's layout_split record (--hotCols provenance,
    ISSUE 5 satellite): a well-formed record validates; wrong-typed fields
    and a non-object record are schema violations."""
    split = {"spec": "auto", "hot_cols": 2048, "coverage": 0.75,
             "residual_mean_nnz": 18.4, "residual_max_nnz": 214,
             "panel_bytes": 166723584}
    good = tmp_path / "good.jsonl"
    good.write_text(json.dumps(
        {"event": "run_start", "seq": 1, "ts": 1.0,
         "manifest": {"config": {}, "config_hash": "x",
                      "layout_split": split}}) + "\n")
    assert tele_schema.check_file(str(good)) == []
    bad = tmp_path / "bad.jsonl"
    bad.write_text(json.dumps(
        {"event": "run_start", "seq": 1, "ts": 1.0,
         "manifest": {"layout_split": {**split, "coverage": "high",
                                       "hot_cols": 2048.5}}}) + "\n")
    errs = tele_schema.check_file(str(bad))
    assert any("coverage" in e for e in errs)
    assert any("hot_cols" in e for e in errs)
    worse = tmp_path / "worse.jsonl"
    worse.write_text(json.dumps(
        {"event": "run_start", "seq": 1, "ts": 1.0,
         "manifest": {"layout_split": [1, 2]}}) + "\n")
    assert any("layout_split" in e
               for e in tele_schema.check_file(str(worse)))


def test_cli_emits_layout_split_in_run_start(tmp_path):
    """A sparse --hotCols CLI run records the resolved split in its
    run_start manifest — machine-readable benchmark provenance."""
    from cocoa_tpu import cli
    from cocoa_tpu.data.synth import synth_sparse, write_libsvm

    path = str(tmp_path / "train.dat")
    write_libsvm(synth_sparse(120, 500, nnz_mean=10, seed=2), path)
    ev = str(tmp_path / "events.jsonl")
    rc = cli.main([
        f"--trainFile={path}", "--numFeatures=500", "--numSplits=4",
        "--numRounds=2", "--localIterFrac=0.2", "--debugIter=2",
        "--mesh=1", "--quiet", "--hotCols=128", f"--events={ev}",
    ])
    assert rc == 0
    assert tele_schema.check_file(ev) == []
    starts = [json.loads(ln) for ln in open(ev)
              if json.loads(ln)["event"] == "run_start"]
    assert len(starts) == 1
    split = starts[0]["manifest"]["layout_split"]
    assert split["hot_cols"] == 128
    assert 0.0 < split["coverage"] <= 1.0
    assert split["residual_mean_nnz"] >= 0.0
    assert split["panel_bytes"] > 0


def test_inactive_bus_is_inert():
    """With no sink configured, emit() is a no-op and solver runs stay on
    the non-streaming executable (no tap, no events, no files)."""
    bus = tele_events.get_bus()
    assert not bus.active()
    assert bus.emit("round_eval", algorithm="X", t=1, primal=0.0) is None
    w, alpha, traj = _backoff_run(device_loop=True)
    assert traj.stopped == "target"
