"""CI fleet-serving smoke: train a model, stack it into a (T, d)
tenant catalogue, serve it through the REAL CLI fleet path
(``--serveReplicas=2 --serveRoute=tenant``), and drive the three fleet
guarantees end to end over plain sockets:

- **per-tenant routing correctness**: every tenant's margins scale
  exactly with that tenant's catalogue row (power-of-two tenant scales
  make the check bit-exact), including across a mid-run catalogue
  hot-swap that both replicas must pick up;
- **zero failed queries under replica death**: one replica is
  SIGKILLed mid-traffic and every subsequent line must still answer
  (requeue, never fail), with the fleet monitor respawning the dead
  replica and the router folding it back in;
- **one compile per (bucket, dtype) per replica process**: each
  replica's event stream carries exactly two ``serve_margins`` compile
  records per process lifetime, whatever T is;
- **sampled query tracing + the live ops plane** (docs/DESIGN.md §22):
  clients prefix ``trace=<id>;`` and the router's ``--traceSample``
  emits schema-valid ``query_trace`` events with router AND replica
  hops filled; ``--statusPort`` answers ``/healthz`` (degraded while
  the SIGKILLed replica is down, ok again after the respawn),
  ``/metrics`` (merged exposition with ``replica="rN"`` labels and the
  tenant-labeled gap-age gauge), and ``/slo`` (rolling attainment over
  the fleet-wide latency histogram);
- **per-replica metrics file ownership**: each replica owns a distinct
  ``<metrics>.r<N>`` textfile; a respawn inherits the SLOT (the new
  process atomically overwrites the dead one's file — its compile
  counter restarts at 2), never interleaves.

Not a pytest file (no ``test_`` prefix): run it directly —

    PYTHONPATH=. python tests/fleet_serve_smoke.py <artifact-dir>

The front door's ``replica_state``/``serve_shed`` stream and the
per-replica ``--events`` streams are schema-validated, and the fleet
gauges (``cocoa_serve_replicas_live``, ``cocoa_serve_requeue_total``)
are grepped out of the metrics textfile.  Exit code 0 = every check
held.  The same mechanics are pinned in-process as tests
(tests/test_serving.py); this script keeps the spawn/SIGKILL/respawn
path — real processes, real sockets, real signals — visible as its own
CI signal with uploadable artifacts.
"""

import json
import os
import re
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
D = 9947
# power-of-two per-tenant scales: (w * s) @ x == s * (w @ x) EXACTLY in
# float, so cross-tenant answers are checkable to the last bit
SCALES = (1.0, 0.5, 0.25, 2.0)
_PID_RE = re.compile(r"replica (r\d+) pid=(\d+) port=(\d+)")


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    outdir = argv[0] if argv else tempfile.mkdtemp(prefix="fleet-smoke-")
    os.makedirs(outdir, exist_ok=True)
    ck = os.path.join(outdir, "ck-train")
    cat = os.path.join(outdir, "ck-catalogue")
    events_path = os.path.join(outdir, "fleet-events.jsonl")
    metrics_path = os.path.join(outdir, "fleet-metrics.prom")
    # the persistent XLA cache would satisfy a replica's warmup from
    # disk and log no compile — opt out so the one-compile-per-bucket
    # pin counts real compiles deterministically
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "COCOA_NO_COMPILE_CACHE": "1"}

    print("fleet-smoke: training the base model (CoCoA+, 40 rounds)",
          flush=True)
    rc = subprocess.run(
        [sys.executable, "-m", "cocoa_tpu.cli",
         "--trainFile=data/small_train.dat", f"--numFeatures={D}",
         "--numSplits=4", "--numRounds=40", "--debugIter=10",
         "--chkptIter=20", f"--chkptDir={ck}", "--localIterFrac=0.1",
         "--lambda=0.001", "--layout=dense", "--math=fast",
         "--gapTarget=1e-4", "--justCoCoA=true", "--quiet"],
        cwd=ROOT, env=env, timeout=600).returncode
    if rc != 0:
        print(f"fleet-smoke FAIL: training exited {rc}")
        return 1

    # stack the trained w into a (T, d) catalogue — the PR-12 fleet's
    # stacked checkpoint shape, written through the production writer
    from cocoa_tpu import checkpoint as ckpt_lib

    meta, w, _ = ckpt_lib.load(ckpt_lib.latest(ck, "CoCoA+"))
    w = np.asarray(w, np.float32)
    w_cat = np.stack([w * s for s in SCALES])
    round0 = int(meta["round"])
    # per-tenant certification metadata rides the stacked checkpoint
    # (docs/DESIGN.md §22) — what the tenant-labeled gap-age gauge and
    # the /metrics plane render from
    now = time.time()
    ckpt_lib.save(cat, "CoCoA+", round0, w_cat, None, gap=1e-4,
                  tenant_gaps=[1e-4] * len(SCALES),
                  tenant_cert_ts=[now - 10.0 * t
                                  for t in range(len(SCALES))])
    print(f"fleet-smoke: catalogue saved — {len(SCALES)} tenants, "
          f"shape {w_cat.shape}, r{round0}", flush=True)

    failures = fleet_phase(cat, round0, events_path, metrics_path, env)
    if failures:
        for msg in failures:
            print(f"fleet-smoke FAIL: {msg}")
        return 1
    print(f"fleet-smoke: OK — routed {len(SCALES)} tenants "
          f"bit-exactly, hot-swapped, survived a replica SIGKILL with "
          f"zero failed queries, schema valid, gauges present "
          f"(artifacts in {outdir})")
    return 0


def fleet_phase(cat, round0, events_path, metrics_path, env) -> list:
    failures = []
    server = subprocess.Popen(
        [sys.executable, "-m", "cocoa_tpu.cli", "--serve=0",
         "--serveReplicas=2", "--serveRoute=tenant",
         f"--chkptDir={cat}", f"--numFeatures={D}",
         "--serveBatch=8,64", "--serveSlaMs=200",
         "--traceSample=4", "--statusPort=0",
         f"--events={events_path}", f"--metrics={metrics_path}"],
        cwd=ROOT, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    lines, pids = [], {}   # pids: replica name -> [pid, pid-after-respawn, ...]
    lock = threading.Lock()

    def drain():
        for line in server.stdout:
            print(f"fleet-smoke: server: {line.rstrip()}", flush=True)
            with lock:
                lines.append(line)
                m = _PID_RE.search(line)
                if m:
                    pids.setdefault(m.group(1), []).append(
                        int(m.group(2)))
    threading.Thread(target=drain, daemon=True).start()

    def wait_for(pred, what, timeout):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with lock:
                got = pred()
            if got:
                return got
            if server.poll() is not None:
                failures.append(f"server exited {server.poll()} "
                                f"while waiting for {what}")
                return None
            time.sleep(0.2)
        failures.append(f"timed out waiting for {what}")
        return None

    try:
        announce = wait_for(
            lambda: next((ln for ln in lines
                          if "fleet listening on" in ln), None),
            "the fleet announce", timeout=600)
        if announce is None:
            return failures
        port = int(announce.split("fleet listening on ")[1]
                   .split()[0].rstrip("(").rsplit(":", 1)[1])
        if "tenants=4" not in announce:
            failures.append(f"announce does not declare the catalogue: "
                            f"{announce.rstrip()}")
        status_ln = wait_for(
            lambda: next((ln for ln in lines
                          if "status listening on" in ln), None),
            "the status-plane announce", timeout=60)
        if status_ln is None:
            return failures
        status_port = int(status_ln.split("status listening on ")[1]
                          .strip().rsplit(":", 1)[1])

        def ops(path):
            import urllib.request
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{status_port}{path}",
                    timeout=30) as r:
                return r.read().decode()

        s = socket.create_connection(("127.0.0.1", port), timeout=60)
        f = s.makefile("rwb")

        def score(tenant, trace=None):
            prefix = f"trace={trace};" if trace else ""
            f.write(f"{prefix}tenant={tenant};3:1.0;5:2.5 "
                    f"7:-1.0;10:0.5\n".encode())
            f.flush()
            return json.loads(f.readline())

        # --- per-tenant routing: margins scale bit-exactly -----------
        base = score(0)   # the line carries 3 ';'-separated queries
        if not (isinstance(base, list) and len(base) == 3
                and all("margin" in r for r in base)):
            return failures + [f"bad tenant-0 response: {base}"]
        for t, scale in enumerate(SCALES):
            resp = score(t)
            if not isinstance(resp, list):
                failures.append(f"tenant {t} got {resp}")
                continue
            for b, r in zip(base, resp):
                if r.get("tenant") != t:
                    failures.append(f"tenant {t} answer tagged "
                                    f"{r.get('tenant')}")
                if r["margin"] != b["margin"] * scale:
                    failures.append(
                        f"tenant {t} margin {r['margin']} != "
                        f"{b['margin']} * {scale} — routing served the "
                        f"wrong catalogue row")
        print("fleet-smoke: all tenants answer bit-exactly against "
              "their catalogue rows", flush=True)

        # --- sampled tracing + the ops plane, pre-drill --------------
        # --traceSample=4 with a deterministic counter: the first
        # trace=-prefixed line is always sampled, so 8 traced lines
        # yield >= 2 query_trace events at the front door
        for k in range(8):
            resp = score(k % len(SCALES), trace=f"{k:08x}")
            if not (isinstance(resp, list)
                    and all("margin" in r for r in resp)):
                failures.append(f"traced query {k} got {resp}")
        hz = json.loads(ops("/healthz"))
        if hz.get("status") != "ok" or hz.get("replicas_live") != 2:
            failures.append(f"pre-drill /healthz not ok: {hz}")
        # the replicas' slot textfiles flush on a 5s heartbeat — wait
        # for the merged exposition to carry both replicas + the
        # tenant-labeled gap age before asserting
        tenant_needle = ('cocoa_model_gap_age_seconds'
                         '{replica="r0",tenant="0"}')
        merged = ops("/metrics")
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline and (
                'replica="r1"' not in merged
                or tenant_needle not in merged):
            time.sleep(1.0)
            merged = ops("/metrics")
        for needle in ('replica="r0"', 'replica="r1"', tenant_needle):
            if needle not in merged:
                failures.append(f"{needle!r} missing from the merged "
                                f"/metrics exposition")
        slo = json.loads(ops("/slo"))
        for field in ("attainment", "burn_fast", "burn_slow",
                      "served_total", "over_sla_total",
                      "replicas_live"):
            if field not in slo:
                failures.append(f"/slo missing {field!r}: {slo}")
        print(f"fleet-smoke: ops plane up — /healthz ok, /metrics "
              f"merged with replica labels, /slo served_total="
              f"{slo.get('served_total')}", flush=True)

        # --- catalogue hot-swap: both replicas must pick it up -------
        from cocoa_tpu import checkpoint as ckpt_lib

        _, w_cat, _ = ckpt_lib.load(ckpt_lib.latest(cat, "CoCoA+"))
        new_round = round0 + 10
        ckpt_lib.save(cat, "CoCoA+", new_round,
                      np.asarray(w_cat) * 0.5, None, gap=1e-5,
                      tenant_gaps=[1e-5] * len(SCALES),
                      tenant_cert_ts=[time.time()] * len(SCALES))
        print(f"fleet-smoke: injected catalogue generation "
              f"r{new_round}", flush=True)
        swapped = {}
        deadline = time.monotonic() + 120
        # tenant 0 homes on r0 and tenant 1 on r1, so seeing the new
        # round on both proves BOTH replicas swapped
        while time.monotonic() < deadline and len(swapped) < 2:
            for t in (0, 1):
                resp = score(t)
                if (isinstance(resp, list) and resp
                        and resp[0].get("round") == new_round):
                    swapped[t] = resp
            time.sleep(0.1)
        if len(swapped) < 2:
            failures.append(f"hot-swap r{new_round} reached only "
                            f"replicas {sorted(swapped)} within 120s")
        elif swapped[0][0]["margin"] != base[0]["margin"] * 0.5:
            failures.append(
                f"post-swap tenant-0 margin {swapped[0][0]['margin']} "
                f"!= half the pre-swap {base[0]['margin']}")
        else:
            print(f"fleet-smoke: both replicas serve r{new_round}, "
                  f"answers halved exactly", flush=True)

        # --- SIGKILL one replica mid-traffic: requeue, never fail ----
        with lock:
            r0_pids = list(pids.get("r0", []))
        if not r0_pids:
            return failures + ["no pid note for replica r0"]
        os.kill(r0_pids[0], signal.SIGKILL)
        print(f"fleet-smoke: SIGKILLed replica r0 (pid "
              f"{r0_pids[0]})", flush=True)
        answered = 0
        for i in range(30):
            resp = score(i % len(SCALES))
            if isinstance(resp, list) and all("margin" in r
                                              for r in resp):
                answered += 1
            else:
                failures.append(f"query {i} after the SIGKILL got "
                                f"{resp} — a dead replica must cost "
                                f"latency, never a failed query")
        print(f"fleet-smoke: {answered}/30 queries answered through "
              f"the kill window", flush=True)

        # mid-drill /healthz: the router marked r0 dead at the first
        # failed forward, so the plane must show it down (degraded)
        # before the monitor's respawn re-registers it
        hz = None
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            hz = json.loads(ops("/healthz"))
            if hz.get("replicas_live") == 1:
                break
            time.sleep(0.5)
        r0_row = (hz or {}).get("replicas", {}).get("r0", {})
        if not hz or hz.get("replicas_live") != 1 \
                or hz.get("status") != "degraded" \
                or r0_row.get("live") is not False:
            failures.append(f"/healthz never showed r0 down after the "
                            f"SIGKILL: {hz}")
        else:
            print("fleet-smoke: /healthz degraded with r0 down "
                  "mid-drill", flush=True)

        # the monitor must respawn r0 (a second pid note) and the
        # respawned replica must serve the LATEST generation
        if wait_for(lambda: len(pids.get("r0", [])) >= 2,
                    "the r0 respawn", timeout=600):
            resp = wait_for(
                lambda: (lambda r: r if isinstance(r, list) and r
                         and r[0].get("round") == new_round
                         else None)(score(0)),
                "the respawned r0 to serve the catalogue",
                timeout=120)
            if resp and resp[0]["margin"] != base[0]["margin"] * 0.5:
                failures.append(
                    f"respawned r0 serves margin {resp[0]['margin']}, "
                    f"expected {base[0]['margin'] * 0.5}")
            else:
                print("fleet-smoke: respawned r0 rejoined routing on "
                      "the injected generation", flush=True)
            hz = json.loads(ops("/healthz"))
            if hz.get("status") != "ok" or hz.get("replicas_live") != 2:
                failures.append(f"post-respawn /healthz not ok: {hz}")
            else:
                print("fleet-smoke: /healthz ok again after the "
                      "respawn", flush=True)
        # a second /slo evaluation gives the burn windows a delta to
        # compute over (two snapshots inside the fast window)
        slo = json.loads(ops("/slo"))
        if not slo.get("served_total"):
            failures.append(f"post-drill /slo shows no served "
                            f"traffic: {slo}")

        f.write(b"shutdown\n")
        f.flush()
        ack = json.loads(f.readline())
        if ack.get("ok") != "shutting down":
            failures.append(f"bad shutdown ack: {ack}")
        s.close()
        rc = server.wait(timeout=120)
        if rc != 0:
            failures.append(f"fleet exited {rc} after shutdown")
    finally:
        if server.poll() is None:
            server.kill()
            server.wait(timeout=10)

    failures += stream_checks(events_path, metrics_path, new_round)
    return failures


def stream_checks(events_path, metrics_path, new_round) -> list:
    """Validate every emitted stream: the front door's router events,
    both replicas' serve streams, and the fleet gauges."""
    from cocoa_tpu.telemetry import schema as tele_schema

    failures = []
    streams = [events_path] + [f"{events_path}.r{i}" for i in (0, 1)]
    for path in streams:
        if not os.path.exists(path):
            failures.append(f"missing event stream {path}")
            continue
        errs = tele_schema.check_file(path)
        if errs:
            failures.append(f"{os.path.basename(path)} schema "
                            f"violations: {errs[:5]}")
    if failures:
        return failures

    # front door: initial live states, the death, the requeue, the
    # respawn, and a clean shutdown
    recs = [json.loads(ln) for ln in open(events_path)]
    states = [r for r in recs if r["event"] == "replica_state"]
    by_state = {}
    for r in states:
        by_state.setdefault(r["state"], []).append(r)
    if len(by_state.get("live", [])) < 3:
        failures.append(f"expected >=3 live replica_state events "
                        f"(2 initial + the respawn), got "
                        f"{len(by_state.get('live', []))}")
    if not by_state.get("dead"):
        failures.append("no dead replica_state event for the SIGKILL")
    requeues = by_state.get("requeue", [])
    if not requeues or not all(r["requeued"] == 1 for r in requeues):
        failures.append(f"expected requeue events with requeued=1, "
                        f"got {requeues}")
    if not any(r["event"] == "run_end"
               and r.get("stopped") == "shutdown" for r in recs):
        failures.append("no run_end stopped=shutdown on the front door")

    # replicas: ONE compile per (bucket, dtype) per process — two
    # buckets, so 2 for r1 and 4 for r0 (original process + respawn,
    # both appending to the same .r0 stream); plus the injected swap
    for i, want in ((0, 4), (1, 2)):
        rrecs = [json.loads(ln) for ln in open(f"{events_path}.r{i}")]
        compiles = [r for r in rrecs if r["event"] == "compile"
                    and "serve_margins" in r["name"]]
        if len(compiles) != want:
            failures.append(
                f"replica r{i} stream has {len(compiles)} "
                f"serve_margins compiles, expected {want} (one per "
                f"bucket per process — the catalogue must not add "
                f"specializations)")
        if not any(r["event"] == "model_swap"
                   and r.get("round") == new_round for r in rrecs):
            failures.append(f"replica r{i} never emitted a model_swap "
                            f"for the injected r{new_round}")

    metrics_text = open(metrics_path).read()
    for needle in ("cocoa_serve_replicas_live 2",
                   "cocoa_serve_shed_total",
                   "cocoa_serve_requeue_total",
                   "cocoa_query_traces_total"):
        if needle not in metrics_text:
            failures.append(f"{needle!r} missing from the fleet "
                            f"metrics textfile")
    m = re.search(r"cocoa_serve_requeue_total (\d+)", metrics_text)
    if m and int(m.group(1)) < 1:
        failures.append("cocoa_serve_requeue_total is 0 after a "
                        "SIGKILL under traffic")

    # sampled query traces: the front door (the router owns fleet
    # emission) must carry schema-valid query_trace events with BOTH
    # the router-side and the replica-side hops filled, and the
    # waterfall assembler must name a dominant hop over them
    qts = [r for r in recs if r["event"] == "query_trace"]
    if len(qts) < 2:
        failures.append(f"expected >=2 query_trace events at the "
                        f"front door (8 traced lines at "
                        f"--traceSample=4), got {len(qts)}")
    for qt in qts:
        for hop in ("router_queue_s", "replica_queue_s", "device_s",
                    "serialize_s", "total_s"):
            if qt.get(hop) is None:
                failures.append(f"query_trace {qt.get('trace_id')} "
                                f"missing hop {hop}: {qt}")
        if qt.get("replica") not in ("r0", "r1"):
            failures.append(f"query_trace names no replica: {qt}")
    from cocoa_tpu.telemetry import trace_report
    wf = trace_report.query_waterfall(qts)
    if qts and wf["dominant_hop"] is None:
        failures.append(f"query waterfall names no dominant hop: {wf}")

    # per-replica metrics SLOT ownership: each replica owns a distinct
    # .r<N> textfile; the respawned r0 process inherited the slot and
    # atomically overwrote it — its compile counter restarts at the
    # fresh process's 2 (the .r0 EVENT stream, which appends, holds 4)
    for i in (0, 1):
        mpath = f"{metrics_path}.r{i}"
        if not os.path.exists(mpath):
            failures.append(f"missing per-replica metrics file {mpath}")
            continue
        mtext = open(mpath).read()
        if "cocoa_model_round" not in mtext:
            failures.append(f"{mpath} carries no model round — not a "
                            f"serve replica's textfile?")
        cm = re.search(r"cocoa_compiles_total (\d+)", mtext)
        want = 2   # one compile per bucket for THIS process lifetime
        if not cm or int(cm.group(1)) != want:
            failures.append(
                f"{mpath} shows cocoa_compiles_total "
                f"{cm.group(1) if cm else 'absent'}, expected {want} — "
                f"the slot file must be owned by exactly the newest "
                f"process in the slot, never interleaved")
    r0_metrics = open(f"{metrics_path}.r0").read() \
        if os.path.exists(f"{metrics_path}.r0") else ""
    if 'cocoa_model_gap_age_seconds{tenant="0"}' not in r0_metrics:
        failures.append("tenant-labeled gap age missing from the "
                        "respawned r0's metrics slot file")
    return failures


if __name__ == "__main__":
    sys.exit(main())
