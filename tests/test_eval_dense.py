"""The dense eval twin for sparse datasets (``eval_dense=True``).

The certificate's full margins pass over a sparse shard gathers one w
element per nonzero; measured through the production rcv1 device-loop
path that eval was 31% of the round time (9.42 -> 6.46 ms/round).  The
twin routes ONLY the full-pass evaluation (ops/rows.eval_margins)
through a dense MXU matvec; every sampled-row training accessor keeps the
CSR layout.  These tests pin both sides of that contract: the eval values
agree to float tolerance, and the TRAINING state is bit-identical with
and without the twin (training must never read it).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from cocoa_tpu.config import DebugParams, Params
from cocoa_tpu.data.sharding import shard_dataset
from cocoa_tpu.evals import objectives
from cocoa_tpu.solvers import run_cocoa, run_dist_gd

K = 4


def _pair(tiny_data, dtype=jnp.float64):
    plain = shard_dataset(tiny_data, k=K, layout="sparse", dtype=dtype)
    twin = shard_dataset(tiny_data, k=K, layout="sparse", dtype=dtype,
                         eval_dense=True)
    return plain, twin


def test_twin_only_in_eval_arrays(tiny_data):
    plain, twin = _pair(tiny_data)
    assert "X_eval" not in plain.shard_arrays()
    sa = twin.shard_arrays()
    assert sa["X_eval"].shape == (K, twin.n_shard, twin.num_features)
    # the sparse training arrays are untouched
    np.testing.assert_array_equal(np.asarray(sa["sp_indices"]),
                                  np.asarray(plain.shard_arrays()["sp_indices"]))


def test_eval_values_match_sparse_eval(tiny_data):
    plain, twin = _pair(tiny_data)
    rng = np.random.default_rng(5)
    w = jnp.asarray(rng.normal(size=tiny_data.num_features))
    alpha = jnp.asarray(rng.random((K, plain.n_shard)))
    for f in (objectives.primal_objective, ):
        np.testing.assert_allclose(f(twin, w, 0.01), f(plain, w, 0.01),
                                   rtol=1e-12, atol=1e-12)
    np.testing.assert_allclose(
        objectives.duality_gap(twin, w, alpha, 0.01),
        objectives.duality_gap(plain, w, alpha, 0.01),
        rtol=1e-12, atol=1e-12)
    np.testing.assert_allclose(
        objectives.classification_error(twin, w),
        objectives.classification_error(plain, w), atol=0)


@pytest.mark.parametrize("math", ["exact", "fast"])
def test_training_state_bit_identical(tiny_data, math):
    """The twin may change logged metrics only by rounding order — the
    TRAINED (w, alpha) must be bit-identical, proving no training path
    reads it.  math="fast" matters: its per-round margins pass uses
    shard_margins, which must keep the gather form (eval_margins is the
    eval-only twin dispatch — ops/rows.py)."""
    plain, twin = _pair(tiny_data)
    p = Params(n=tiny_data.n, num_rounds=5, local_iters=8, lam=0.01)
    d = DebugParams(debug_iter=2, seed=0)
    w_p, a_p, traj_p = run_cocoa(plain, p, d, plus=True, quiet=True,
                                 math=math)
    w_t, a_t, traj_t = run_cocoa(twin, p, d, plus=True, quiet=True,
                                 math=math)
    np.testing.assert_array_equal(np.asarray(w_t), np.asarray(w_p))
    np.testing.assert_array_equal(np.asarray(a_t), np.asarray(a_p))
    for rp, rt in zip(traj_p.records, traj_t.records):
        np.testing.assert_allclose(rt.gap, rp.gap, rtol=1e-12, atol=1e-12)


def test_distgd_training_bit_identical(tiny_data):
    """DistGD's deterministic full TRAINING pass rides shard_margins,
    which ignores the twin — its w must be BIT-identical either way."""
    plain, twin = _pair(tiny_data)
    p = Params(n=tiny_data.n, num_rounds=3, local_iters=1, lam=0.01)
    d = DebugParams(debug_iter=3, seed=0)
    w_p, _ = run_dist_gd(plain, p, d, quiet=True)
    w_t, _ = run_dist_gd(twin, p, d, quiet=True)
    np.testing.assert_array_equal(np.asarray(w_t), np.asarray(w_p))


def test_eval_dense_validation(tiny_data):
    with pytest.raises(ValueError, match="sparse"):
        shard_dataset(tiny_data, k=K, layout="dense", eval_dense=True)
