"""Device-side (in-jit) index-table generation vs the host samplers.

Round-4 measurement: through the tunneled device, h2d transfers collapse to
~10 MB/s once multi-GB shards are resident, so per-round (K, H) index tables
cost more to SHIP than the fused kernel round costs to RUN.  The fix is the
reference's own structure — draw indices inside the worker
(CoCoA.scala:144,151) — as in-jit generation (utils/prng.py
device_sample_per_shard, base.IndexSampler.tables_from_ts).  These tests pin
the device tables to the host tables bit-for-bit:

- ``reference``: the 48-bit java.util.Random LCG replayed on 12-bit int32
  limbs, including the modulo-rejection filtering (exercised here with
  bounds just above a power of two, where ~half of all draws reject —
  far harsher than any real shard size).
- ``jax``: the same counter-hash stream (utils/prng.py) expanded host-side
  or in-jit — one integer-arithmetic implementation, so host ≡ device by
  construction (jax.random's batched-key threefry was abandoned for this
  path: ~100 ms per dispatch through the tunnel).
- ``permuted``: the same per-(seed, shard, epoch) Feistel-bijection
  permutations either way; also re-pins the reshuffling invariants
  (coverage, chunk invariance, continuity) on that stream.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cocoa_tpu.solvers.base import IndexSampler
from cocoa_tpu.utils.prng import (
    device_replay_ok,
    device_sample_per_shard,
    sample_indices_per_shard,
)


def _host(seed, t0, c, h, counts):
    tab = sample_indices_per_shard(seed, range(t0, t0 + c), h, counts)
    return np.swapaxes(tab, 0, 1)  # (C, K, H)


@pytest.mark.parametrize("seed,t0,c,h,counts", [
    (0, 1, 5, 17, [33]),
    (5, 1, 3, 10, [33, 64, 100]),            # mixed pow2 / non-pow2
    (99, 7, 4, 64, [50000, 2531, 1, 7]),     # big, tiny, and n=1 shards
    (123456, 1000, 2, 128, [20242]),
])
def test_reference_device_tables_bit_exact(seed, t0, c, h, counts):
    ts = jnp.arange(t0, t0 + c, dtype=jnp.int32)
    dev = np.asarray(jax.jit(
        lambda ts: device_sample_per_shard(seed, ts, h, counts)
    )(ts))
    np.testing.assert_array_equal(dev, _host(seed, t0, c, h, counts))


def test_reference_device_tables_heavy_rejection():
    # bound just above 2^30: java's nextInt rejects ~50% of raw draws, so
    # every lane exercises the in-jit compaction path
    counts = [(1 << 30) + 1, (1 << 30) + 3]
    dev = np.asarray(device_sample_per_shard(
        3, jnp.arange(1, 4, dtype=jnp.int32), 40, counts))
    np.testing.assert_array_equal(dev, _host(3, 1, 3, 40, counts))


def test_reference_device_replay_guard():
    assert device_replay_ok(0, 1000)
    assert not device_replay_ok(-1, 10)
    assert not device_replay_ok((1 << 31) - 5, 10)


@pytest.mark.parametrize("mode", ["reference", "jax", "permuted"])
def test_sampler_device_equals_host(mode):
    counts = np.array([13, 16, 9])
    host = IndexSampler(mode, seed=5, h=7, counts=counts, device=False)
    dev = IndexSampler(mode, seed=5, h=7, counts=counts, device=True)
    want = np.asarray(host.chunk_indices(3, 6))
    spec = dev.chunk_indices(3, 6)
    assert set(spec) == {"t"} and spec["t"].shape == (6,)
    got = np.asarray(jax.jit(dev.tables_from_ts)(spec["t"]))
    np.testing.assert_array_equal(got, want)
    # and all values in range
    for s, cnt in enumerate(counts):
        assert got[:, s, :].min() >= 0 and got[:, s, :].max() < cnt


def test_permuted_epoch_coverage_and_continuity():
    counts = np.array([10, 35, 5])
    s = IndexSampler("permuted", seed=3, h=5, counts=counts)
    tab = np.asarray(s.chunk_indices(1, 40))          # (40, 3, 5) = 200 steps
    for k, cnt in enumerate(counts):
        stream = tab[:, k, :].reshape(-1)
        for e in range(len(stream) // cnt):
            epoch = stream[e * cnt:(e + 1) * cnt]
            assert sorted(epoch.tolist()) == list(range(cnt))


def test_permuted_chunk_invariance():
    counts = np.array([11, 8])
    s1 = IndexSampler("permuted", seed=5, h=7, counts=counts)
    s2 = IndexSampler("permuted", seed=5, h=7, counts=counts)
    whole = np.asarray(s1.chunk_indices(1, 12))
    parts = np.concatenate([
        np.asarray(s2.chunk_indices(1, 5)),
        np.asarray(s2.chunk_indices(6, 4)),
        np.asarray(s2.chunk_indices(10, 3)),
    ])
    np.testing.assert_array_equal(whole, parts)
    # different seed ⇒ different stream
    s3 = IndexSampler("permuted", seed=6, h=7, counts=counts)
    assert not np.array_equal(np.asarray(s3.chunk_indices(1, 12)), whole)


def test_ints_per_round_and_cache_token():
    s = IndexSampler("reference", 0, 50, np.array([100, 100]))
    assert s.ints_per_round() == 100
    s.device = True
    assert s.ints_per_round() == 1
    t1 = s.cache_token()
    s2 = IndexSampler("reference", 0, 50, np.array([100, 100]), device=True)
    assert s2.cache_token() == t1
    s3 = IndexSampler("reference", 1, 50, np.array([100, 100]), device=True)
    assert s3.cache_token() != t1


def test_solver_trajectory_device_vs_host_sampling(tiny_data):
    """End to end: CoCoA+ chunked with device sampling == host sampling,
    for every rng mode (bit-identical tables ⇒ bit-identical runs)."""
    from cocoa_tpu.config import DebugParams, Params
    from cocoa_tpu.data import shard_dataset
    from cocoa_tpu.solvers import run_cocoa

    ds = shard_dataset(tiny_data, k=4, layout="dense", dtype=jnp.float64)
    params = Params(n=tiny_data.n, num_rounds=8, local_iters=12, lam=1e-2)
    debug = DebugParams(debug_iter=4, seed=0)
    for mode in ("reference", "jax", "permuted"):
        runs = {}
        for sampling in ("host", "device"):
            w, a, traj = run_cocoa(
                ds, params, debug, plus=True, quiet=True, scan_chunk=4,
                rng=mode, sampling=sampling,
            )
            runs[sampling] = (np.asarray(w), np.asarray(a),
                              [r.gap for r in traj.records])
        np.testing.assert_array_equal(runs["host"][0], runs["device"][0])
        np.testing.assert_array_equal(runs["host"][1], runs["device"][1])
        assert runs["host"][2] == runs["device"][2]


def test_sgd_device_sampling(tiny_data):
    """η(t) solvers: the TsSampler spec path generates idxs in-jit."""
    from cocoa_tpu.config import DebugParams, Params
    from cocoa_tpu.data import shard_dataset
    from cocoa_tpu.solvers import run_sgd

    ds = shard_dataset(tiny_data, k=4, layout="dense", dtype=jnp.float64)
    params = Params(n=tiny_data.n, num_rounds=6, local_iters=10, lam=1e-2)
    debug = DebugParams(debug_iter=3, seed=0)
    outs = {}
    for sampling in ("host", "device"):
        w, traj = run_sgd(ds, params, debug, local=True, quiet=True,
                          scan_chunk=3, sampling=sampling)
        outs[sampling] = np.asarray(w)
    np.testing.assert_array_equal(outs["host"], outs["device"])


def test_sampling_flag_validation(tiny_data):
    from cocoa_tpu.config import DebugParams, Params
    from cocoa_tpu.data import shard_dataset
    from cocoa_tpu.solvers import run_cocoa
    from cocoa_tpu.solvers.base import resolve_sampling

    ds = shard_dataset(tiny_data, k=2, layout="dense", dtype=jnp.float64)
    params = Params(n=tiny_data.n, num_rounds=2, local_iters=4, lam=1e-2)
    debug = DebugParams(debug_iter=2, seed=0)
    with pytest.raises(ValueError, match="sampling"):
        run_cocoa(ds, params, debug, plus=True, quiet=True,
                  sampling="bogus")
    # device replay outside the int32 seed range must refuse explicitly...
    s = IndexSampler("reference", (1 << 31) - 1, 4, ds.counts)
    with pytest.raises(ValueError, match="device sampling"):
        resolve_sampling("device", s, 10)
    # ...and fall back silently under auto
    assert resolve_sampling("auto", s, 10) is False
