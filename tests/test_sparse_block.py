"""Sparse block-chain kernel (round 6 tentpole) — in-kernel Gram from SMEM
CSR streams, no densify (ops/pallas_sparse.sparse_block_gram/_apply feeding
ops/pallas_chain.chain_block_batched through local_sdca_block_batched's
``sparse_gram`` path).

The contract mirrors tests/test_block.py: the sparse block path consumes the
SAME sampled index stream as the sequential fast path and is identical to it
in real arithmetic, so trajectory parity to fp tolerance — not mere
convergence parity — is what is pinned, in CPU interpret mode
(``pl.pallas_call(..., interpret=True)``) so CI exercises the kernels
without a TPU.  Coverage: all three SDCA modes, f32 and f64, the masked tail
(H % B != 0), duplicate draws inside a block, multi-block rounds with the Δw
carry, the SMEM row-segment tiling, generic losses, the layout-driven auto
dispatch, the driver integration, and the ``--blockSize=auto`` CLI flag.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from cocoa_tpu.config import DebugParams, Params
from cocoa_tpu.data.libsvm import LibsvmData
from cocoa_tpu.data.sharding import shard_dataset
from cocoa_tpu.ops.local_sdca import local_sdca_block_batched, local_sdca_fast
from cocoa_tpu.ops.rows import shard_margins
from cocoa_tpu.solvers import run_cocoa
from cocoa_tpu.utils.prng import sample_indices_per_shard

K = 4


def _sparse_ds(tiny_data, dtype=jnp.float32, k=K):
    ds = shard_dataset(tiny_data, k=k, layout="sparse", dtype=dtype)
    return ds, ds.shard_arrays()


def _compare_per_shard(da_b, dw_b, sa, w, alpha, idxs, n, mode, sigma,
                       rtol, atol, loss="hinge", smoothing=1.0):
    d = w.shape[0]
    for s in range(alpha.shape[0]):
        shard = {kk: v[s] for kk, v in sa.items()}
        m0 = shard_margins(w, shard)
        da_f, dw_f = local_sdca_fast(
            m0, alpha[s], shard, idxs[s], 0.01, n,
            jnp.zeros(d, w.dtype), mode=mode, sigma=sigma, loss=loss,
            smoothing=smoothing,
        )
        np.testing.assert_allclose(np.asarray(da_b[s]), np.asarray(da_f),
                                   rtol=rtol, atol=atol)
        np.testing.assert_allclose(np.asarray(dw_b[s]), np.asarray(dw_f),
                                   rtol=rtol, atol=atol)


@pytest.mark.slow
@pytest.mark.parametrize("mode,sigma", [
    ("cocoa", 1.0),
    # tier-1 budget (rounds 22/24): every arm now rides -m slow — the
    # dedicated CI parity step runs this file unfiltered, so the parity
    # contract keeps its own CI signal
    pytest.param("plus", 4.0, marks=pytest.mark.slow),
    pytest.param("frozen", 1.0, marks=pytest.mark.slow)])
def test_sparse_block_kernel_matches_fast(tiny_data, mode, sigma):
    """f32 interpret-mode parity against the sequential fast path — masked
    tail (H=37 vs B=128) and within-block duplicate draws included (37
    draws from 24-row shards guarantee repeats)."""
    ds, sa = _sparse_ds(tiny_data)
    rng = np.random.default_rng(5)
    d = tiny_data.num_features
    w = jnp.asarray(rng.normal(size=d) * 0.1, jnp.float32)
    alpha = jnp.asarray(
        np.clip(rng.normal(size=(K, ds.n_shard)) * 0.3 + 0.3, 0, 1),
        jnp.float32,
    )
    idxs = jnp.asarray(
        sample_indices_per_shard(7, range(1, 2), 37, ds.counts)[:, 0, :]
    )
    da_b, dw_b = local_sdca_block_batched(
        w, alpha, sa, idxs, 0.01, tiny_data.n, mode=mode, sigma=sigma,
        block=128, interpret=True, sparse_gram=True,
    )
    _compare_per_shard(da_b, dw_b, sa, w, alpha, idxs, tiny_data.n,
                       mode, sigma, rtol=2e-4, atol=1e-6)


@pytest.mark.slow
def test_sparse_block_kernel_f64(tiny_data):
    """Float64 interpret mode pins the algebra tightly (the fp-association
    differences shrink to ~1e-12) — same tolerance contract as the f64
    chain tests in test_block.py."""
    ds, sa = _sparse_ds(tiny_data, dtype=jnp.float64)
    rng = np.random.default_rng(11)
    d = tiny_data.num_features
    w = jnp.asarray(rng.normal(size=d) * 0.1)
    alpha = jnp.asarray(
        np.clip(rng.normal(size=(K, ds.n_shard)) * 0.3 + 0.3, 0, 1))
    idxs = jnp.asarray(
        sample_indices_per_shard(3, range(1, 2), 37, ds.counts)[:, 0, :]
    )
    da_b, dw_b = local_sdca_block_batched(
        w, alpha, sa, idxs, 0.01, tiny_data.n, mode="plus", sigma=4.0,
        block=128, interpret=True, sparse_gram=True,
    )
    _compare_per_shard(da_b, dw_b, sa, w, alpha, idxs, tiny_data.n,
                       "plus", 4.0, rtol=1e-9, atol=1e-12)


@pytest.mark.slow
@pytest.mark.parametrize("mode,sigma", [
    ("cocoa", 1.0),
    # tier-1 budget (rounds 22/24): every arm now rides -m slow — the
    # dedicated CI parity step runs this file unfiltered, so the parity
    # contract keeps its own CI signal
    pytest.param("plus", 4.0, marks=pytest.mark.slow),
    pytest.param("frozen", 1.0, marks=pytest.mark.slow)])
def test_sparse_block_segmented_smem(tiny_data, monkeypatch, mode, sigma):
    """The SMEM row-segment tiling (the rcv1 regime, where a whole block's
    streams exceed the budget): shrink the budget so B=128 splits into
    four (32, 32) Gram tiles, and run H=200 so the round spans TWO blocks
    — the cross-block Δw carry through the [w | Δw] array is covered."""
    import cocoa_tpu.ops.pallas_sparse as ps

    ds, sa = _sparse_ds(tiny_data)
    w_nnz = int(sa["sp_indices"].shape[-1])
    group = min(ps.GROUP, w_nnz)
    w_r = -(-w_nnz // group) * group
    monkeypatch.setattr(ps, "SMEM_IDX_BUDGET", 16 * 32 * w_r)
    assert ps.seg_rows(128, w_nnz) == 32
    rng = np.random.default_rng(5)
    d = tiny_data.num_features
    w = jnp.asarray(rng.normal(size=d) * 0.1, jnp.float32)
    alpha = jnp.asarray(
        np.clip(rng.normal(size=(K, ds.n_shard)) * 0.3 + 0.3, 0, 1),
        jnp.float32,
    )
    idxs = jnp.asarray(
        sample_indices_per_shard(7, range(1, 2), 200, ds.counts)[:, 0, :]
    )
    da_b, dw_b = local_sdca_block_batched(
        w, alpha, sa, idxs, 0.01, tiny_data.n, mode=mode, sigma=sigma,
        block=128, interpret=True, sparse_gram=True,
    )
    _compare_per_shard(da_b, dw_b, sa, w, alpha, idxs, tiny_data.n,
                       mode, sigma, rtol=2e-4, atol=1e-6)


@pytest.mark.slow
@pytest.mark.parametrize("loss,smoothing", [("smooth_hinge", 0.5),
                                            ("logistic", 1.0)])
def test_sparse_block_generic_losses(tiny_data, loss, smoothing):
    """Non-hinge losses ride the chain kernel's generic branch; the sparse
    Gram/margins feed it the identical (scal, gq) contract."""
    ds, sa = _sparse_ds(tiny_data)
    rng = np.random.default_rng(9)
    d = tiny_data.num_features
    w = jnp.asarray(rng.normal(size=d) * 0.1, jnp.float32)
    alpha = jnp.asarray(
        np.clip(rng.normal(size=(K, ds.n_shard)) * 0.3 + 0.3, 0.01, 0.99),
        jnp.float32,
    )
    idxs = jnp.asarray(
        sample_indices_per_shard(7, range(1, 2), 37, ds.counts)[:, 0, :]
    )
    da_b, dw_b = local_sdca_block_batched(
        w, alpha, sa, idxs, 0.01, tiny_data.n, mode="plus", sigma=4.0,
        loss=loss, smoothing=smoothing, block=128, interpret=True,
        sparse_gram=True,
    )
    _compare_per_shard(da_b, dw_b, sa, w, alpha, idxs, tiny_data.n,
                       "plus", 4.0, rtol=2e-4, atol=1e-6,
                       loss=loss, smoothing=smoothing)


@pytest.mark.slow
def test_sparse_block_duplicates_exact(tiny_data):
    """A pathological stream — every draw the same index — makes the Gram
    self-coupling plus the equality tile carry the whole sequential
    recurrence (‖x‖² on the diagonal never enters: only i < j entries are
    read, the α chaining rides eq)."""
    ds, sa = _sparse_ds(tiny_data, dtype=jnp.float64, k=1)
    d = tiny_data.num_features
    w = jnp.zeros(d)
    alpha = jnp.zeros((1, ds.n_shard))
    idxs = jnp.full((1, 16), 3, dtype=jnp.int32)
    da_b, dw_b = local_sdca_block_batched(
        w, alpha, sa, idxs, 0.01, tiny_data.n, mode="plus", sigma=4.0,
        block=128, interpret=True, sparse_gram=True,
    )
    _compare_per_shard(da_b, dw_b, sa, w, alpha, idxs, tiny_data.n,
                       "plus", 4.0, rtol=1e-9, atol=1e-12)


def test_seg_rows_and_fits():
    """SMEM segmentation plan at real scales: a whole rcv1-like block
    (W≈548 GROUP-rounds to 576 → 590 KB of streams) does NOT fit the
    512 KB budget whole, splits into S=32 segments, and sparse_chain_fits
    accepts the flagship shape; pathologically wide rows are rejected."""
    from cocoa_tpu.ops.pallas_sparse import (
        SMEM_IDX_BUDGET, seg_rows, sparse_chain_fits,
    )

    assert 16 * 128 * 576 > SMEM_IDX_BUDGET          # whole block misses
    assert seg_rows(128, 548) == 32                  # the rcv1 plan
    assert seg_rows(128, 15) == 128                  # tiny rows: one tile
    assert seg_rows(128, 5000) == 0                  # even S=8 misses
    assert sparse_chain_fits(8, 2544, 47236, 548, 128, 4)   # rcv1 flagship
    assert not sparse_chain_fits(8, 2544, 47236, 548, 100, 4)  # B % 128
    assert not sparse_chain_fits(8, 2544, 47236, 5000, 128, 4)


@pytest.mark.slow
def test_sparse_block_auto_dispatch(monkeypatch):
    """The block dispatch picks the sparse Gram path FROM THE LAYOUT: a
    sparse dataset whose densified tile cannot fit the fused kernel
    (d=12000 at K=2, B=128 needs ~18 MB of half-tile) routes through
    sparse_block_gram with no explicit override; the dense layout of the
    same rows never does."""
    import cocoa_tpu.ops.pallas_sparse as ps
    from cocoa_tpu.ops.pallas_chain import fused_fits

    rng = np.random.default_rng(3)
    n, d, nnz = 64, 12000, 12
    cols = np.stack([rng.choice(d, size=nnz, replace=False) for _ in range(n)])
    cols.sort(axis=1)
    vals = rng.normal(size=(n, nnz))
    y = np.where(rng.random(n) > 0.5, 1.0, -1.0)
    data = LibsvmData(
        labels=y, indptr=np.arange(0, (n + 1) * nnz, nnz, dtype=np.int64),
        indices=cols.reshape(-1).astype(np.int32),
        values=vals.reshape(-1), num_features=d,
    )
    k = 2
    ds = shard_dataset(data, k=k, layout="sparse", dtype=jnp.float32)
    sa = ds.shard_arrays()
    assert not fused_fits(k, 128, d, 4, ds.n_shard)

    seen = []
    real = ps.sparse_block_gram

    def spy(*args, **kw):
        seen.append(True)
        return real(*args, **kw)

    monkeypatch.setattr(ps, "sparse_block_gram", spy)
    w = jnp.zeros(d, jnp.float32)
    alpha = jnp.zeros((k, ds.n_shard), jnp.float32)
    idxs = jnp.asarray(
        sample_indices_per_shard(1, range(1, 2), 8, ds.counts)[:, 0, :]
    )
    da, dw = local_sdca_block_batched(
        w, alpha, sa, idxs, 0.01, n, mode="plus", sigma=2.0, block=128,
        interpret=True,                       # sparse_gram=None → auto
    )
    assert seen, "auto dispatch must take the sparse Gram path"
    # and the numbers still match the sequential fast path
    _compare_per_shard(da, dw, sa, w, alpha, idxs, n, "plus", 2.0,
                       rtol=2e-4, atol=1e-6)


def test_sparse_block_rejects_dense_layout(tiny_data):
    ds = shard_dataset(tiny_data, k=K, layout="dense", dtype=jnp.float32)
    with pytest.raises(ValueError, match="padded-CSR"):
        local_sdca_block_batched(
            jnp.zeros(tiny_data.num_features, jnp.float32),
            jnp.zeros((K, ds.n_shard), jnp.float32), ds.shard_arrays(),
            jnp.zeros((K, 4), jnp.int32), 0.01, tiny_data.n,
            block=128, interpret=True, sparse_gram=True,
        )


@pytest.mark.slow
def test_sparse_block_through_driver(tiny_data):
    """Driver integration (the chunked per_round_batched routing): the
    sparse Gram block solver reproduces the no-block fast-path trajectory
    through run_cocoa, including the final duality gap."""
    ds = shard_dataset(tiny_data, k=K, layout="sparse", dtype=jnp.float32)
    p = Params(n=tiny_data.n, num_rounds=6, local_iters=20, lam=0.01)
    dbg = DebugParams(debug_iter=3, seed=0)
    w_f, a_f, traj_f = run_cocoa(ds, p, dbg, plus=True, quiet=True,
                                 math="fast", pallas=False)
    w_b, a_b, traj_b = run_cocoa(ds, p, dbg, plus=True, quiet=True,
                                 math="fast", block_size=128,
                                 block_chain="pallas_interpret",
                                 block_sparse_gram=True)
    np.testing.assert_allclose(np.asarray(w_b), np.asarray(w_f),
                               rtol=2e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(a_b), np.asarray(a_f),
                               rtol=2e-4, atol=1e-6)
    assert traj_b.records[-1].gap == pytest.approx(
        traj_f.records[-1].gap, rel=1e-3)


def test_auto_block_size_per_layout(tiny_data):
    """--blockSize=auto resolution mirrors the dispatch: dense → 128;
    sparse → 128 when the fused OR CSR Gram path fits, 0 (sequential)
    when neither does; non-f32 → 0."""
    from cocoa_tpu.ops.pallas_chain import fused_fits
    from cocoa_tpu.solvers.cocoa import auto_block_size

    ds_d = shard_dataset(tiny_data, k=K, layout="dense", dtype=jnp.float32)
    ds_s = shard_dataset(tiny_data, k=K, layout="sparse", dtype=jnp.float32)
    assert auto_block_size(ds_d, K, jnp.float32) == 128
    assert auto_block_size(ds_s, K, jnp.float32) == 128
    assert auto_block_size(ds_d, K, jnp.float64) == 0
    # big-d (fused cannot hold the densified tile) + streams too wide for
    # the SMEM segmentation: neither block kernel wins — sequential stays
    rng = np.random.default_rng(0)
    n, d, nnz = 32, 12000, 4
    cols = np.stack([np.sort(rng.choice(d, size=nnz, replace=False))
                     for _ in range(n)])
    data = LibsvmData(
        labels=np.where(rng.random(n) > 0.5, 1.0, -1.0),
        indptr=np.arange(0, (n + 1) * nnz, nnz, dtype=np.int64),
        indices=cols.reshape(-1).astype(np.int32),
        values=rng.normal(size=n * nnz), num_features=d,
    )
    ds_wide = shard_dataset(data, k=2, layout="sparse", dtype=jnp.float32,
                            max_nnz=5000)
    assert not fused_fits(2, 128, d, 4, ds_wide.n_shard)
    assert auto_block_size(ds_wide, 2, jnp.float32) == 0


@pytest.mark.slow
def test_cli_block_size_auto(tmp_path, capsys):
    """--blockSize=auto through the CLI: rejected without --math=fast,
    resolved per layout otherwise."""
    from cocoa_tpu import cli
    from cocoa_tpu.data.synth import synth_dense, write_libsvm

    path = str(tmp_path / "train.dat")
    write_libsvm(synth_dense(48, 16, seed=0), path)

    rc = cli.main([f"--trainFile={path}", "--numFeatures=16",
                   "--blockSize=auto"])
    assert rc == 2
    assert "--math=fast" in capsys.readouterr().err

    rc = cli.main([
        f"--trainFile={path}", "--numFeatures=16", "--numSplits=4",
        "--numRounds=3", "--localIterFrac=0.5", "--lambda=.01",
        "--justCoCoA=true", "--debugIter=3", "--math=fast",
        "--blockSize=auto", "--mesh=1",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "blockSize=auto: using 128 for the dense layout" in out
    assert "CoCoA+" in out
