"""The device-resident σ′ anneal schedule (--sigmaSchedule=anneal).

The sigma=auto trial-and-rerun (--sigmaSchedule=trial, the A/B control)
pays for a wrong aggressive guess twice: a guarded ~stall-window trial
PLUS a full restart.  The anneal schedule carries σ′ in the drive*
ladder's loop state instead: when the stall watch fires, σ′ backs off
multiplicatively toward the safe K·γ IN PLACE — same dispatch, same
while_loop, current iterate kept.  Soundness: the primal-dual
correspondence w = (1/λn)·Σ y·α·x and the α ∈ [0,1]^n box are maintained
by the update rule under ANY σ′, so the exact duality-gap certificate
survives the switch (docs/DESIGN.md §3e).

These tests pin, on shards built to NEED the full σ′ = K (every shard
holds identical rows — the adversarial coherence the K·γ bound protects
against):

- the in-loop backoff fires and the run still certifies, with no restart;
- host-chunked and device-loop drivers produce identical trajectories;
- a run that never backs off is BIT-IDENTICAL to the fixed-σ′ run;
- a mid-schedule checkpoint resume is BIT-IDENTICAL to uninterrupted;
- --sigmaSchedule=trial is preserved bit-exact as the A/B control;
- the --warmStart scanned handoff equals the manual two-run handoff.
"""

import dataclasses
import os

import numpy as np
import pytest

import jax.numpy as jnp

from cocoa_tpu import checkpoint as ckpt_lib
from cocoa_tpu.config import DebugParams, Params
from cocoa_tpu.data.sharding import shard_dataset
from cocoa_tpu.data.synth import synth_sparse
from cocoa_tpu.solvers import base, run_cocoa
from test_divergence import _coherent_dataset

K, LAM = 4, 1e-4


def _anneal_run(device_loop, sigma=1.0, num_rounds=1600, tmp=None,
                chkpt_iter=0, quiet=True, **kw):
    """Divergence-prone config: σ′ start 1.0 = K·γ/4 on adversarially
    coherent shards (≤ 3.5·γ·K/8 = 1.75 — the forced-divergence regime the
    acceptance criteria name), cadence 25 so the stall window is the
    calibration 12 evals."""
    ds, n = _coherent_dataset(k=K)
    params = Params(n=n, num_rounds=num_rounds, local_iters=16, lam=LAM,
                    sigma=sigma)
    debug = DebugParams(debug_iter=25, seed=0,
                        chkpt_iter=chkpt_iter or num_rounds + 1,
                        chkpt_dir=str(tmp) if tmp else "")
    return run_cocoa(ds, params, debug, plus=True, quiet=quiet, math="fast",
                     device_loop=device_loop, gap_target=1e-3, rng="jax",
                     sigma_schedule="anneal", **kw)


def _sigma_transitions(traj):
    sig = [(r.round, r.sigma) for r in traj.records if r.sigma is not None]
    return [sig[0]] + [b for a, b in zip(sig, sig[1:]) if b[1] != a[1]]


def test_anneal_levels_ladder():
    assert base.anneal_levels(4.0, 8.0) == (4.0, 8.0)
    assert base.anneal_levels(3.5, 8.0) == (3.5, 7.0, 8.0)
    assert base.anneal_levels(1.0, 4.0) == (1.0, 2.0, 4.0)
    # start at/above safe: the schedule is inert (one rung)
    assert base.anneal_levels(8.0, 8.0) == (8.0,)
    assert base.anneal_levels(9.0, 8.0) == (8.0,)
    # an absurdly low start is capped: the last step jumps to safe
    lv = base.anneal_levels(1e-6, 8.0)
    assert len(lv) <= base.MAX_SIGMA_LEVELS and lv[-1] == 8.0
    assert all(a < b for a, b in zip(lv, lv[1:]))


def test_sched_host_step_is_gapwatch_twin():
    """Same windowed no-improvement semantics as base._GapWatch, plus the
    backoff action (stage += 1, fresh watch) instead of a bail-out.  (The
    twin matches the DEVICE watch bit-for-bit — NaN/None gaps map to +inf
    like the in-loop code, a policy only primal-only evals ever see; the
    anneal paths always have a real gap.)"""
    s = base.sched_init_array(1)
    s = np.asarray(s)
    seq = [1.0, 0.9, 0.7, 5.0, 0.6, 0.55]
    fires = []
    for g in seq:
        s, backed = base.sched_host_step(s, g, stall_evals=3, n_stages=2)
        fires.append(backed)
    # the _GapWatch fixture from test_divergence: reset at 0.7, then three
    # straight non-improving evals fire the window
    assert fires == [False] * 5 + [True]
    assert s[0] == 1.0 and s[1] == 0.0 and np.isinf(s[2]) and np.isinf(s[3])
    # at the last stage the watch is inert: it never "fires" again
    for g in (0.55, 0.55, 0.55, 0.55, 0.55):
        s, backed = base.sched_host_step(s, g, stall_evals=3, n_stages=2)
        assert not backed
    assert s[0] == 1.0


def test_anneal_backs_off_in_loop_and_certifies_host():
    """σ′ = K/4 on coherent shards diverges; the schedule must back off
    within one stall window of the watch firing — in place, no restart —
    and still certify the gap target inside the round budget."""
    w, alpha, traj = _anneal_run(device_loop=False)
    assert traj.stopped == "target"
    assert traj.records[-1].gap <= 1e-3
    trans = _sigma_transitions(traj)
    assert len(trans) >= 2, "the schedule never backed off"
    sigmas = [s for _, s in trans]
    assert sigmas[0] == 1.0                      # aggressive start
    assert all(a < b for a, b in zip(sigmas, sigmas[1:]))  # monotone backoff
    assert sigmas[-1] <= K * 1.0                 # never past the safe bound
    # the first backoff cannot beat the stall window (12 evals × 25 rounds)
    assert trans[1][0] >= 12 * 25
    # and the whole run (backoff included) beats the budget by a wide margin
    assert traj.records[-1].round < 1600


def test_anneal_device_loop_identical_to_host():
    """The while_loop-resident controller and the host-chunked twin make
    identical decisions and produce identical states (same f32 watch
    arithmetic, same branch kernels)."""
    w_h, a_h, t_h = _anneal_run(device_loop=False)
    w_d, a_d, t_d = _anneal_run(device_loop=True)
    np.testing.assert_array_equal(np.asarray(w_h), np.asarray(w_d))
    np.testing.assert_array_equal(np.asarray(a_h), np.asarray(a_d))
    assert _sigma_transitions(t_h) == _sigma_transitions(t_d)
    assert t_d.stopped == "target"
    assert [r.round for r in t_h.records] == [r.round for r in t_d.records]


def test_anneal_no_backoff_is_bitexact_vs_fixed_sigma():
    """Benign data at σ′ = K/2: the watch never fires, and the scheduled
    run must be bit-identical to the plain fixed-σ′ run with the same
    chunking — the stage-0 branch IS the fixed kernel."""
    data = synth_sparse(512, 128, nnz_mean=12, seed=3)
    ds = shard_dataset(data, k=4, layout="dense", dtype=jnp.float32)
    debug = DebugParams(debug_iter=10, seed=0)
    params = Params(n=data.n, num_rounds=100, local_iters=16, lam=1e-2,
                    sigma=2.0)
    kw = dict(plus=True, quiet=True, math="fast", gap_target=1e-6,
              rng="permuted")
    w_a, a_a, t_a = run_cocoa(ds, params, debug, sigma_schedule="anneal",
                              **kw)
    w_f, a_f, t_f = run_cocoa(ds, params, debug, scan_chunk=1, **kw)
    np.testing.assert_array_equal(np.asarray(w_a), np.asarray(w_f))
    np.testing.assert_array_equal(np.asarray(a_a), np.asarray(a_f))
    assert all(r.sigma == 2.0 for r in t_a.records)


def test_anneal_checkpoint_resume_mid_schedule_bit_identical(tmp_path,
                                                             monkeypatch):
    """Resume from a checkpoint taken MID-WINDOW at stage 0 (stall counters
    accumulated, no backoff yet): the restored schedule state must
    reproduce the uninterrupted run bit-for-bit — the backoff fires at the
    same round and the final state is identical."""
    # this test resumes from a SPECIFIC mid-run generation (r400, chosen
    # for its mid-window stage-0 sched state); keep every generation so
    # the default keep-2 pruning cannot rotate it away
    monkeypatch.setattr(ckpt_lib, "KEEP_GENERATIONS", 1000)
    w0, a0, t0 = _anneal_run(device_loop=True, tmp=tmp_path, chkpt_iter=100)
    assert t0.stopped == "target"
    path = os.path.join(str(tmp_path), "CoCoA+-r000400.npz")
    meta, wc, ac = ckpt_lib.load(path)
    sched = meta.get("sched")
    assert sched is not None and len(sched) == base.SCHED_LEN
    assert sched[0] == 0.0 and sched[1] > 0, \
        "the test premise needs a mid-window stage-0 checkpoint"
    assert sched[4] == meta["round"] + 1
    w_r, a_r, t_r = _anneal_run(
        device_loop=True, w_init=wc, alpha_init=ac,
        start_round=meta["round"] + 1,
        sched_init=np.asarray(sched, np.float32))
    assert t_r.stopped == "target"
    np.testing.assert_array_equal(np.asarray(w0), np.asarray(w_r))
    np.testing.assert_array_equal(np.asarray(a0), np.asarray(a_r))


def test_anneal_resume_without_sched_falls_back_to_safe(capsys):
    """A resumed run with no schedule state (pre-schedule checkpoint /
    bare w_init) cannot know its stage — it continues at the safe σ′,
    exactly like the trial path's resumed-run rule."""
    rng = np.random.default_rng(0)
    w0 = jnp.asarray(rng.normal(size=16) * 0.01, jnp.float32)
    w, a, traj = _anneal_run(device_loop=False, sigma="auto",
                             num_rounds=200, w_init=w0, start_round=5,
                             quiet=False)
    out = capsys.readouterr().out
    assert "continuing with the safe" in out


def test_sigma_auto_defaults_to_anneal_and_starts_aggressive():
    """--sigma=auto now rides the anneal schedule by default: the run
    starts at K·γ/2 with no trial/rerun machinery (on benign data it
    simply certifies at the aggressive σ′)."""
    ds, n = _coherent_dataset(k=K)
    params = Params(n=n, num_rounds=400, local_iters=16, lam=LAM,
                    sigma="auto")
    debug = DebugParams(debug_iter=4, seed=0)
    w, alpha, traj = run_cocoa(ds, params, debug, plus=True, quiet=True,
                               math="fast", gap_target=1e-3, rng="jax")
    assert traj.stopped == "target"
    assert traj.records[-1].sigma == K / 2.0


def test_trial_schedule_preserved_bit_exact():
    """--sigmaSchedule=trial is the A/B control: sigma=auto under it runs
    the aggressive trial exactly as the pre-schedule code did — on data
    where the trial certifies, bit-identical to the fixed σ′=K·γ/2 run."""
    ds, n = _coherent_dataset(k=K)
    debug = DebugParams(debug_iter=4, seed=0)
    p_auto = Params(n=n, num_rounds=400, local_iters=16, lam=LAM,
                    sigma="auto")
    p_half = Params(n=n, num_rounds=400, local_iters=16, lam=LAM,
                    sigma=K / 2.0)
    kw = dict(plus=True, quiet=True, math="fast", gap_target=1e-3,
              rng="jax")
    w_t, a_t, t_t = run_cocoa(ds, p_auto, debug, sigma_schedule="trial",
                              **kw)
    w_f, a_f, t_f = run_cocoa(ds, p_half, debug, **kw)
    assert t_t.stopped == "target"
    np.testing.assert_array_equal(np.asarray(w_t), np.asarray(w_f))
    np.testing.assert_array_equal(np.asarray(a_t), np.asarray(a_f))


def test_anneal_validations():
    ds, n = _coherent_dataset(k=K)
    params = Params(n=n, num_rounds=10, local_iters=4, lam=LAM,
                    sigma="auto")
    debug = DebugParams(debug_iter=2, seed=0)
    # anneal (the default) requires the gap-target path
    with pytest.raises(ValueError, match="gapTarget"):
        run_cocoa(ds, params, debug, plus=True, quiet=True)
    # ... and the guard (its firing IS the backoff trigger)
    with pytest.raises(ValueError, match="divergenceGuard"):
        run_cocoa(ds, params, debug, plus=True, quiet=True,
                  gap_target=1e-3, divergence_guard="off")
    # trial is only meaningful as the sigma=auto control
    with pytest.raises(ValueError, match="trial"):
        run_cocoa(ds, dataclasses.replace(params, sigma=2.0), debug,
                  plus=True, quiet=True, sigma_schedule="trial")
    with pytest.raises(ValueError, match="trial|anneal"):
        run_cocoa(ds, params, debug, plus=True, quiet=True,
                  sigma_schedule="nope")


def test_anneal_explicit_sigma_start():
    """--sigma=<float> --sigmaSchedule=anneal anneals from that start —
    the ladder's first rung is the explicit σ′, the last is safe K·γ."""
    w, alpha, traj = _anneal_run(device_loop=False, sigma=1.0,
                                 num_rounds=1600)
    sigmas = sorted({r.sigma for r in traj.records if r.sigma is not None})
    assert sigmas[0] == 1.0
    assert all(s in (1.0, 2.0, 4.0) for s in sigmas)


# --- the --warmStart scanned handoff ---------------------------------------


def _warm_ds():
    data = synth_sparse(512, 128, nnz_mean=12, seed=3)
    return shard_dataset(data, k=4, layout="dense", dtype=jnp.float32), data.n


@pytest.mark.slow
def test_warm_start_equals_manual_handoff():
    """The in-loop smooth_hinge→hinge handoff must equal the manual
    two-run procedure (SWEEPS.md 'warm smooth_hinge' rows) bit-for-bit:
    warm run to round W, then a hinge run resumed from its state."""
    ds, n = _warm_ds()
    debug = DebugParams(debug_iter=10, seed=0)
    p_hinge = Params(n=n, num_rounds=100, local_iters=16, lam=1e-2)
    kw = dict(plus=True, quiet=True, math="fast", rng="permuted")
    w_w, a_w, t_w = run_cocoa(ds, p_hinge, debug, warm_start=(0.5, 30),
                              **kw)
    p_warm = dataclasses.replace(p_hinge, num_rounds=30,
                                 loss="smooth_hinge", smoothing=0.5)
    w_1, a_1, _ = run_cocoa(ds, p_warm, debug, scan_chunk=1, **kw)
    w_2, a_2, _ = run_cocoa(ds, p_hinge, debug, scan_chunk=1, w_init=w_1,
                            alpha_init=a_1, start_round=31, **kw)
    np.testing.assert_array_equal(np.asarray(w_w), np.asarray(w_2))
    np.testing.assert_array_equal(np.asarray(a_w), np.asarray(a_2))
    # the device loop runs the same scanned handoff
    w_d, a_d, _ = run_cocoa(ds, p_hinge, debug, warm_start=(0.5, 30),
                            device_loop=True, **kw)
    np.testing.assert_array_equal(np.asarray(w_d), np.asarray(w_w))


def test_warm_start_rounds_up_to_cadence(capsys):
    ds, n = _warm_ds()
    debug = DebugParams(debug_iter=10, seed=0)
    p = Params(n=n, num_rounds=50, local_iters=16, lam=1e-2)
    w_a, a_a, _ = run_cocoa(ds, p, debug, warm_start=(0.5, 23), plus=True,
                            math="fast", rng="permuted", quiet=False)
    assert "rounded up to round 30" in capsys.readouterr().out
    w_b, a_b, _ = run_cocoa(ds, p, debug, warm_start=(0.5, 30), plus=True,
                            math="fast", rng="permuted", quiet=True)
    np.testing.assert_array_equal(np.asarray(w_a), np.asarray(w_b))


def test_warm_start_validations():
    ds, n = _warm_ds()
    debug = DebugParams(debug_iter=10, seed=0)
    p = Params(n=n, num_rounds=50, local_iters=16, lam=1e-2,
               loss="logistic")
    with pytest.raises(ValueError, match="hinge"):
        run_cocoa(ds, p, debug, plus=True, quiet=True,
                  warm_start=(0.5, 30))
    p2 = Params(n=n, num_rounds=50, local_iters=16, lam=1e-2)
    with pytest.raises(ValueError, match="smoothing"):
        run_cocoa(ds, p2, debug, plus=True, quiet=True,
                  warm_start=(0.0, 30))
    with pytest.raises(ValueError, match="rounds"):
        run_cocoa(ds, p2, debug, plus=True, quiet=True,
                  warm_start=(0.5, 0))
    with pytest.raises(ValueError, match="debugIter"):
        run_cocoa(ds, p2, DebugParams(debug_iter=0, seed=0), plus=True,
                  quiet=True, warm_start=(0.5, 30))


def test_warm_start_combines_with_anneal():
    """warm phase + σ′ schedule share one device loop: the branch table is
    the (stage × phase) product and both selectors ride the sched leaf."""
    ds, n = _warm_ds()
    debug = DebugParams(debug_iter=10, seed=0)
    p = Params(n=n, num_rounds=100, local_iters=16, lam=1e-2, sigma="auto")
    w, alpha, traj = run_cocoa(ds, p, debug, plus=True, quiet=True,
                               math="fast", rng="permuted",
                               gap_target=1e-6, warm_start=(0.5, 30),
                               device_loop=True)
    assert traj.records[-1].sigma is not None


@pytest.mark.slow
def test_rcv1_synth_anneal_certifies_at_575_rounds_no_restart():
    """The acceptance pin: the rcv1-synth production config (H=253,
    permuted, γ=1, λ=1e-4) under --sigma=auto --sigmaSchedule=anneal
    certifies the 1e-4 gap in ≤ 575 rounds — the measured σ′=K/2 sweet
    spot (benchmarks/SWEEPS.md) — with zero backoffs and zero restarts."""
    n, d, k = 20242, 47236, 8
    data = synth_sparse(n, d, nnz_mean=75, seed=0)
    ds = shard_dataset(data, k=k, layout="sparse", dtype=jnp.float32,
                       eval_dense=True)
    h = n // k // 10          # 253
    params = Params(n=n, num_rounds=1600, local_iters=h, lam=1e-4,
                    sigma="auto")
    debug = DebugParams(debug_iter=25, seed=0)
    w, alpha, traj = run_cocoa(ds, params, debug, plus=True, quiet=True,
                               math="fast", device_loop=True,
                               gap_target=1e-4, rng="permuted")
    assert traj.stopped == "target"
    assert traj.records[-1].round <= 575
    assert traj.records[-1].gap <= 1e-4
    # zero-detour: the aggressive start held — no backoff ever fired
    assert all(r.sigma == k / 2.0 for r in traj.records)
