#!/bin/bash
# Multi-host pod launcher — the analogue of the reference's spark-submit
# cluster launcher (run-demo-cluster.sh:3-9).  Run the SAME command on every
# host of the pod slice; JAX discovers peers through the coordinator:
#
#   COCOA_COORDINATOR=<host0-addr:port> ./run-demo-cluster.sh \
#       --trainFile=... --numFeatures=... [flags]
#
# --master=<addr:port> (the reference's flag, hingeDriver.scala:23) is
# honored as the coordinator address too; process id / process count are
# auto-detected on TPU pods (jax.distributed.initialize), or set
# COCOA_PROCESS_ID / COCOA_NUM_PROCESSES explicitly.
cd "$(dirname "$0")"
ARGS=()
[ -n "$COCOA_COORDINATOR" ] && ARGS+=("--master=$COCOA_COORDINATOR")
exec python -m cocoa_tpu.cli "${ARGS[@]}" "$@"
