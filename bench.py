"""Benchmark: wall-clock to a 1e-4 duality gap, CoCoA+ on the reference demo
config (data/small_train.dat, K=4, H=50, λ=1e-3 — run-demo-local.sh:2-9).

Prints ONE JSON line:
    {"metric": ..., "value": seconds, "unit": "s", "vs_baseline": speedup}

``vs_baseline`` is the speedup over the reference implementation proxy: the
same algorithm, same RNG, same convergence criterion executed by the literal
NumPy oracle of the Scala update rules (tests/oracle.py).  The actual Spark
reference cannot run in this environment (sbt needs the network); the oracle
executes the identical per-step math single-threaded, which flatters the
reference if anything (no JVM/Spark scheduling overhead).  The oracle time is
measured once and pinned here (same machine class, see BASELINE.md); set
COCOA_BENCH_BASELINE=measure to re-measure it live.
"""

from __future__ import annotations

import json
import os
import platform
import sys
import time

# Pinned oracle wall-clock for this config (median of repeated runs on this
# machine; see module docstring).  Re-measure with COCOA_BENCH_BASELINE=measure.
# The pin is only trusted when the machine fingerprint below still matches —
# on any other machine class the oracle is re-measured live instead of
# silently comparing against a stale constant.
ORACLE_BASELINE_S = 2.11
ORACLE_FINGERPRINT = "Intel(R) Xeon(R) Processor @ 2.10GHz|x86_64|1"


def machine_fingerprint() -> str:
    """cpu model | arch | core count — enough to detect a machine-class
    change that would invalidate the pinned oracle time."""
    model = platform.processor() or ""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("model name"):
                    model = line.split(":", 1)[1].strip()
                    break
    except OSError:
        pass
    return f"{model}|{platform.machine()}|{os.cpu_count()}"

GAP_TARGET = 1e-4
MAX_ROUNDS = 600  # the demo config crosses 1e-4 around round ~440
DEBUG_ITER = 10
LAM = 1e-3
K = 4
H = 50
_REF_TRAIN = "/root/reference/data/small_train.dat"
TRAIN = (_REF_TRAIN if os.path.exists(_REF_TRAIN) else
         os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "data", "small_train.dat"))  # committed twin
D = 9947


def _enable_compile_cache():
    """Persistent XLA compilation cache (utils/compile_cache.py): the
    gap-run + slope executables recompile identically across bench
    invocations, and first compiles through the tunnel were a large part
    of the 25-minute deadline budget.  Returns the cache directory (None
    when disabled) so the first-run breakdown can classify hit vs miss."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from cocoa_tpu.utils import compile_cache

    return compile_cache.enable()


def _cache_entries(cache_dir) -> int:
    """Number of persistent-cache entries (0 when disabled/absent)."""
    if not cache_dir or not os.path.isdir(cache_dir):
        return 0
    return sum(len(fs) for _, _, fs in os.walk(cache_dir))


def run_tpu(cache_dir=None):
    """Returns (steady_seconds, fixed_overhead_s, raw_best_s,
    raw_first_run_s, compile_cache_mode, comm_rounds) to reach GAP_TARGET.

    ``raw_first_run_s`` is the stopwatch on the FIRST invocation — trace +
    compile (persistent-cache hit or miss, classified by whether the run
    added cache entries) + first dispatch + fetch — reported alongside
    the slope-measured steady state so the 0.0x-second headline cannot be
    misread as a cold-start claim.

    The RAW wall-clock of one run through a tunneled device carries
    hundreds of ms of dispatch+fetch latency that varies run-to-run by more
    than this whole workload — round 2's recorded headline swung
    10.5x -> 8.7x on that noise alone while the kernels got faster.  So the
    headline is SLOPE-measured (the same method benchmarks/kernels.py
    uses — see benchmarks/slope.py, the shared implementation): after the
    gap-targeted run determines the round count R and verifies the
    certificate, fixed-round runs at R and m·R (identical per-round work,
    eval cadence and all) give

        per_round = (T(mR) - T(R)) / ((m-1)R)
        steady    = per_round * R          (the headline)
        fixed     = T(R) - steady          (dispatch/fetch, reported
                                            separately)

    with m escalated until the span dominates the tunnel jitter.

    Every fixed cost — dispatch, fetch, host-side index sampling, trace
    cache lookups — cancels in the difference; what remains scales with
    rounds, which is exactly the work the metric is about."""
    import jax.numpy as jnp

    from cocoa_tpu.config import DebugParams, Params
    from cocoa_tpu.data import load_libsvm, shard_dataset
    from cocoa_tpu.solvers import run_cocoa

    data = load_libsvm(TRAIN, D)
    # dense layout: the TPU-native choice — the padded-CSR gather/scatter
    # path costs ~10x more per SDCA step on TPU (measured 57 vs 4 ms per
    # 10-round chunk on this config); device_loop runs the entire
    # train-until-gap-target loop as one XLA while_loop (one dispatch, one
    # host fetch — a host round-trip through the tunneled device is ~90ms)
    ds = shard_dataset(data, k=K, layout="dense", dtype=jnp.float32)
    debug = DebugParams(debug_iter=DEBUG_ITER, seed=0)
    # math="fast" + auto-Pallas: margins decomposition (one MXU matvec per
    # round) with the VMEM-resident Pallas inner loop on TPU — equal in real
    # arithmetic to the reference order, same 440-round trajectory
    kw = dict(plus=True, quiet=True, device_loop=True, math="fast")

    # gap-targeted run: verifies the certificate and fixes the round count.
    # The first invocation is timed too — it carries trace + compile (or
    # persistent-cache hit) + the first dispatch, the fixed costs a user's
    # stopwatch sees once per process.
    params = Params(n=data.n, num_rounds=MAX_ROUNDS, local_iters=H, lam=LAM)
    entries_before = _cache_entries(cache_dir)
    t0 = time.perf_counter()
    run_cocoa(ds, params, debug, gap_target=GAP_TARGET, **kw)
    raw_first = time.perf_counter() - t0
    cache_mode = ("disabled" if cache_dir is None else
                  "miss" if _cache_entries(cache_dir) > entries_before
                  else "hit")
    t0 = time.perf_counter()
    w, alpha, traj = run_cocoa(ds, params, debug, gap_target=GAP_TARGET,
                               **kw)
    raw = time.perf_counter() - t0
    last = traj.records[-1]
    if last.gap is None or last.gap > GAP_TARGET:
        raise RuntimeError(
            f"did not reach gap {GAP_TARGET} within {MAX_ROUNDS} rounds "
            f"(last gap {last.gap})"
        )
    rounds = last.round

    # slope via the shared helper (benchmarks/slope.py): the demo
    # workload's steady state (~0.1 s) is SMALLER than the tunnel's
    # per-run jitter, so the helper escalates the second point until the
    # span dominates the noise (rounds past the gap crossing do identical
    # per-round work — the kernels are value-independent)
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "benchmarks"))
    from slope import slope_time

    def make_run(nr):
        p = Params(n=data.n, num_rounds=nr, local_iters=H, lam=LAM)
        return lambda: run_cocoa(ds, p, debug, **kw)

    sr = slope_time(make_run, rounds, min_span_s=1.0, reps=5)
    return sr.steady_s, sr.fixed_s, raw, raw_first, cache_mode, rounds


def run_oracle_baseline() -> float:
    """The reference-math proxy, timed to the same convergence criterion."""
    import numpy as np

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "tests"))
    import oracle
    from cocoa_tpu.data import load_libsvm
    from cocoa_tpu.data.sharding import split_sizes
    from cocoa_tpu.utils.prng import sample_indices

    data = load_libsvm(TRAIN, D)
    X, y = data.to_dense(), data.labels
    sizes = split_sizes(data.n, K)
    offs = np.concatenate([[0], np.cumsum(sizes)])
    shards = [(X[offs[i]:offs[i + 1]], y[offs[i]:offs[i + 1]]) for i in range(K)]

    t0 = time.perf_counter()
    w = np.zeros(D)
    alphas = [np.zeros(Xk.shape[0]) for Xk, _ in shards]
    sigma = float(K)  # gamma = 1
    for t in range(1, MAX_ROUNDS + 1):
        dw_sum = np.zeros_like(w)
        for s, (Xk, yk) in enumerate(shards):
            idxs = sample_indices(0, range(t, t + 1), H, Xk.shape[0])[0]
            da, dw = oracle.local_sdca(
                Xk, yk, w, alphas[s], idxs, LAM, data.n, True, sigma
            )
            alphas[s] = alphas[s] + da  # gamma = 1
            dw_sum += dw
        w = w + dw_sum  # gamma = 1
        if t % DEBUG_ITER == 0:
            total_alpha = float(sum(a.sum() for a in alphas))
            gap = oracle.duality_gap(X, y, w, total_alpha, LAM)
            if gap <= GAP_TARGET:
                break
    return time.perf_counter() - t0


def _arm_deadline(minutes: float = 25.0) -> None:
    """Hard exit if the run wedges: the tunneled device can die mid-session
    (observed round 4 — backend init then blocks forever), and an infinite
    hang is strictly worse for the caller than a clean nonzero exit."""
    import threading

    def boom():
        print(f"bench: exceeded the {minutes:.0f}-minute deadline — "
              f"device/tunnel likely unreachable; aborting", file=sys.stderr,
              flush=True)
        os._exit(3)

    t = threading.Timer(minutes * 60.0, boom)
    t.daemon = True
    t.start()


def main() -> int:
    _arm_deadline(float(os.environ.get("COCOA_BENCH_DEADLINE_MIN", "25")))
    cache_dir = _enable_compile_cache()
    mode = os.environ.get("COCOA_BENCH_BASELINE", "")
    elapsed, fixed, raw, raw_first, cache_mode, rounds = run_tpu(cache_dir)
    fpr = machine_fingerprint()
    # one-line fixed-cost breakdown (VERDICT r5 weak #6): what separates
    # the slope-measured steady state from a user's stopwatch — the
    # first-run trace/compile (cache hit or miss), and the per-run
    # dispatch+fetch the slope cancels
    print(f"bench: fixed-cost breakdown — first run {raw_first:.3f}s "
          f"(compile cache {cache_mode}: trace+compile+first-dispatch "
          f"{max(0.0, raw_first - raw):.3f}s over a warm run), warm raw "
          f"run {raw:.3f}s = steady {elapsed:.3f}s + dispatch/fetch "
          f"{fixed:.3f}s (+ tunnel jitter)", file=sys.stderr)
    if mode == "measure":
        baseline, baseline_mode = run_oracle_baseline(), "measured"
        print(f"bench: pinned oracle {ORACLE_BASELINE_S}s, live-measured "
              f"{baseline:.3f}s ({fpr})", file=sys.stderr)
    elif ORACLE_BASELINE_S is not None and fpr == ORACLE_FINGERPRINT:
        baseline, baseline_mode = ORACLE_BASELINE_S, "pinned"
    else:
        # no pin, or the machine class changed since the pin was taken —
        # either way re-measure rather than report a fiction
        baseline, baseline_mode = run_oracle_baseline(), "measured"
        why = ("no pinned oracle time" if ORACLE_BASELINE_S is None else
               f"machine fingerprint {fpr!r} != pinned {ORACLE_FINGERPRINT!r}")
        print(f"bench: {why}; oracle re-measured live ({baseline:.3f}s)",
              file=sys.stderr)
    # the north-star target (BASELINE.json) is argued against an 8-executor
    # Spark cluster.  The demo config has K=4 partitions, so even 8 executors
    # can use at most 4-way parallelism; vs_baseline_parallel_oracle divides
    # the oracle by that ideal speedup — the honest denominator (real Spark
    # adds JVM/scheduling overhead on top, so the true ratio sits between
    # the two numbers).
    ideal_workers = min(8, K)
    print(json.dumps({
        "metric": "wallclock_to_1e-4_duality_gap (CoCoA+ demo config, "
                  f"{rounds} comm-rounds, slope-measured steady state)",
        "value": round(elapsed, 3),
        "unit": "s",
        "vs_baseline": round(baseline / elapsed, 2),
        "vs_baseline_parallel_oracle": round(
            baseline / ideal_workers / elapsed, 2),
        # the tunnel's dispatch+fetch, measured separately — what a raw
        # single-run stopwatch adds on top of the steady-state time
        "fixed_overhead_s": round(fixed, 3),
        "raw_best_s": round(raw, 3),
        # the stopwatch on the FIRST invocation (trace + compile-or-cache
        # + first dispatch + fetch): the cold number next to the
        # steady-state headline so neither can be misread as the other
        "raw_first_run_s": round(raw_first, 3),
        "compile_cache": cache_mode,
        "baseline_s": round(baseline, 3),
        "baseline_mode": baseline_mode,
        "baseline_fingerprint_match": fpr == ORACLE_FINGERPRINT,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
